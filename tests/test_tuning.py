"""HP tuning tests: search space, the four suggestion algorithms, the study
controller end-to-end on the fake API server, and the suggestion service.

Reference test model: katib smoke = create StudyJob CR, poll condition
(``/root/reference/testing/katib_studyjob_test.py``). The fake-cluster tier
lets us drive entire studies to completion in-process instead.
"""

import json
import random
import urllib.request

import pytest

from kubeflow_tpu.k8s import FakeKubeClient
from kubeflow_tpu.manifests.components.tpujob_operator import (
    API_VERSION as TPUJOB_API_VERSION,
    TPUJOB_KIND,
)
from kubeflow_tpu.config.deployment import ComponentSpec, DeploymentConfig
from kubeflow_tpu.manifests.registry import render_component
from kubeflow_tpu.tuning import (
    BayesianOptimization,
    GridSearch,
    Hyperband,
    RandomSearch,
    SearchSpace,
    StudyController,
    StudySpec,
    TrialRecord,
    report_trial_metrics,
    study,
)
from kubeflow_tpu.tuning.service import handle_suggest, serve
from kubeflow_tpu.tuning.study import STUDY_API_VERSION, STUDY_KIND, TRIAL_KIND


SPACE_DICTS = [
    {"name": "lr", "type": "double", "min": 1e-4, "max": 1e-1, "log": True},
    {"name": "layers", "type": "int", "min": 1, "max": 8},
    {"name": "opt", "type": "categorical", "choices": ["adam", "sgd"]},
    {"name": "bs", "type": "discrete", "values": [16, 32, 64]},
]


# -- search space ----------------------------------------------------------

def test_space_sampling_within_bounds():
    space = SearchSpace.from_dicts(SPACE_DICTS)
    rng = random.Random(0)
    for _ in range(200):
        s = space.sample(rng)
        assert 1e-4 <= s["lr"] <= 1e-1
        assert 1 <= s["layers"] <= 8
        assert s["opt"] in ("adam", "sgd")
        assert s["bs"] in (16, 32, 64)


def test_space_encode_decode_roundtrip():
    space = SearchSpace.from_dicts(SPACE_DICTS)
    rng = random.Random(1)
    for _ in range(50):
        s = space.sample(rng)
        u = space.encode(s)
        assert len(u) == space.dim
        back = space.decode(u)
        assert back["opt"] == s["opt"]
        assert back["bs"] == s["bs"]
        assert back["layers"] == s["layers"]
        assert back["lr"] == pytest.approx(s["lr"], rel=1e-6)


def test_grid_enumeration():
    space = SearchSpace.from_dicts(SPACE_DICTS)
    combos = space.grid(points_per_double=3)
    assert len(combos) == 3 * 3 * 2 * 3  # lr×layers×opt×bs
    assert len({json.dumps(c, sort_keys=True, default=str)
                for c in combos}) == len(combos)


# -- algorithms ------------------------------------------------------------

def _quadratic(params):
    # max at lr=0.01 (log-space center-ish), layers=4
    import math

    return -((math.log10(params["lr"]) + 2) ** 2) - 0.1 * (params["layers"] - 4) ** 2


def test_random_search_deterministic_per_history_length():
    space = SearchSpace.from_dicts(SPACE_DICTS)
    a = RandomSearch(space, seed=7).suggest([], 3)
    b = RandomSearch(space, seed=7).suggest([], 3)
    assert a == b
    c = RandomSearch(space, seed=8).suggest([], 3)
    assert a != c


def test_grid_search_resumes_and_exhausts():
    space = SearchSpace.from_dicts([
        {"name": "x", "type": "discrete", "values": [1, 2, 3]},
    ])
    gs = GridSearch(space)
    first = gs.suggest([], 2)
    assert [p["x"] for p in first] == [1, 2]
    rest = gs.suggest([TrialRecord(p) for p in first], 5)
    assert [p["x"] for p in rest] == [3]  # exhausted, returns fewer


def test_bayesian_beats_random_on_quadratic():
    space = SearchSpace.from_dicts(SPACE_DICTS[:2])  # lr + layers
    trials = []
    bo = BayesianOptimization(space, seed=3, settings={"n_initial": 6})
    for _ in range(24):
        (params,) = bo.suggest(trials, 1)
        trials.append(TrialRecord(params, _quadratic(params)))
    best_bo = max(t.objective for t in trials)

    rng_trials = []
    rs = RandomSearch(space, seed=3)
    for _ in range(24):
        (params,) = rs.suggest(rng_trials, 1)
        rng_trials.append(TrialRecord(params, _quadratic(params)))
    best_rs = max(t.objective for t in rng_trials)
    assert best_bo >= best_rs - 1e-9
    assert best_bo > -0.35  # actually found the basin


def test_hyperband_schedule_and_promotion():
    space = SearchSpace.from_dicts([
        {"name": "x", "type": "double", "min": 0.0, "max": 1.0},
    ])
    hb = Hyperband(space, seed=0, settings={
        "resource": "steps", "max_resource": 9, "eta": 3})
    sched = hb.schedule()
    # R=9, eta=3 → brackets s=2,1,0
    assert len(sched) == 3
    assert sched[0][0]["n"] >= sched[0][1]["n"] >= sched[0][2]["n"]
    assert sched[0][0]["r"] < sched[0][1]["r"] < sched[0][2]["r"]

    trials = []
    # fill bracket 0 rung 0
    rung0 = hb.suggest(trials, sched[0][0]["n"])
    assert all(p["steps"] == sched[0][0]["r"] for p in rung0)
    # objective = x: top configs must be the largest x
    trials = [TrialRecord(p, p["x"]) for p in rung0]
    rung1 = hb.suggest(trials, sched[0][1]["n"])
    assert len(rung1) == sched[0][1]["n"]
    assert all(p["steps"] == sched[0][1]["r"] for p in rung1)
    promoted_x = {p["x"] for p in rung1}
    top_x = {p["x"] for p in sorted(rung0, key=lambda p: -p["x"])[:len(rung1)]}
    assert promoted_x == top_x


def test_hyperband_waits_for_incomplete_rung():
    space = SearchSpace.from_dicts([
        {"name": "x", "type": "double", "min": 0.0, "max": 1.0},
    ])
    hb = Hyperband(space, settings={"resource": "steps", "max_resource": 9})
    n0 = hb.schedule()[0][0]["n"]
    rung0 = hb.suggest([], n0)
    # one trial still running (objective None) → no promotions yet
    trials = [TrialRecord(p, p["x"]) for p in rung0[:-1]]
    trials.append(TrialRecord(rung0[-1], None))
    assert hb.suggest(trials, 4) == []


# -- study controller end-to-end ------------------------------------------

def _study_spec(**over):
    spec = {
        "objective": {"type": "maximize", "metric": "accuracy"},
        "algorithm": {"name": "random"},
        "parameters": [
            {"name": "lr", "type": "double", "min": 0.01, "max": 1.0},
        ],
        "parallelTrials": 2,
        "maxTrials": 6,
        "trialTemplate": {
            "image": "kubeflow-tpu/examples:latest",
            "args": ["--lr=${trialParameters.lr}"],
            "slices": 1,
            "hostsPerSlice": 1,
        },
    }
    spec.update(over)
    return spec


def _run_study(client, ctrl, ns="default", name="s", max_rounds=50,
               objective=lambda p: 1.0 - (float(p["lr"]) - 0.3) ** 2):
    """Drive reconcile + a fake trial executor until the study is terminal."""
    for _ in range(max_rounds):
        ctrl.reconcile(ns, name)
        s = client.get(STUDY_API_VERSION, STUDY_KIND, ns, name)
        if s.get("status", {}).get("phase") in ("Succeeded", "Failed"):
            return s
        # fake executor: complete every running trial job
        for job in client.list(TPUJOB_API_VERSION, TPUJOB_KIND, ns):
            if job.get("status", {}).get("phase") == "Succeeded":
                continue
            params = {}
            for trial in client.list(STUDY_API_VERSION, TRIAL_KIND, ns):
                if trial["metadata"]["name"] == job["metadata"]["name"]:
                    params = trial["spec"]["parameters"]
            report_trial_metrics(client, ns, job["metadata"]["name"],
                                 {"accuracy": objective(params)})
            job.setdefault("status", {})["phase"] = "Succeeded"
            client.update_status(job)
    raise AssertionError("study did not terminate")


def test_study_runs_to_completion_with_best_trial():
    client = FakeKubeClient()
    ctrl = StudyController(client)
    client.create(study("s", "default", _study_spec()))
    s = _run_study(client, ctrl)
    st = s["status"]
    assert st["phase"] == "Succeeded"
    assert st["trials"] == 6
    assert st["trialsSucceeded"] == 6
    best = st["bestTrial"]
    assert best["objective"] == pytest.approx(
        1.0 - (float(best["parameters"]["lr"]) - 0.3) ** 2)
    # substitution reached the job args
    job = client.get(TPUJOB_API_VERSION, TPUJOB_KIND, "default", best["name"])
    assert job["spec"]["args"] == [f"--lr={best['parameters']['lr']}"]
    assert job["spec"]["env"]["KFTPU_TRIAL_NAME"] == best["name"]


def test_study_respects_parallelism():
    client = FakeKubeClient()
    ctrl = StudyController(client)
    client.create(study("s", "default", _study_spec(parallelTrials=2)))
    ctrl.reconcile("default", "s")
    jobs = client.list(TPUJOB_API_VERSION, TPUJOB_KIND, "default")
    assert len(jobs) == 2  # no more than parallelTrials in flight
    ctrl.reconcile("default", "s")
    assert len(client.list(TPUJOB_API_VERSION, TPUJOB_KIND, "default")) == 2


def test_study_goal_short_circuits():
    client = FakeKubeClient()
    ctrl = StudyController(client)
    spec = _study_spec(objective={"type": "maximize", "metric": "accuracy",
                                  "goal": 0.5}, maxTrials=50)
    client.create(study("s", "default", spec))
    s = _run_study(client, ctrl, objective=lambda p: 0.9)
    assert s["status"]["phase"] == "Succeeded"
    assert s["status"]["trials"] < 50


def test_study_minimize_objective():
    client = FakeKubeClient()
    ctrl = StudyController(client)
    spec = _study_spec(objective={"type": "minimize", "metric": "accuracy"},
                       maxTrials=4)
    client.create(study("s", "default", spec))
    s = _run_study(client, ctrl,
                   objective=lambda p: (float(p["lr"]) - 0.3) ** 2)
    best = s["status"]["bestTrial"]
    for trial in client.list(STUDY_API_VERSION, TRIAL_KIND, "default"):
        obs = trial.get("status", {}).get("observation", {})
        if obs:
            assert best["objective"] <= obs["accuracy"] + 1e-12


def test_study_fails_without_metrics():
    client = FakeKubeClient()
    ctrl = StudyController(client)
    client.create(study("s", "default", _study_spec(
        maxTrials=2, parallelTrials=2, maxFailedTrials=1)))
    ctrl.reconcile("default", "s")
    # jobs succeed but never report the metric → trials fail → study fails
    for job in client.list(TPUJOB_API_VERSION, TPUJOB_KIND, "default"):
        job.setdefault("status", {})["phase"] = "Succeeded"
        client.update_status(job)
    for _ in range(5):
        ctrl.reconcile("default", "s")
    s = client.get(STUDY_API_VERSION, STUDY_KIND, "default", "s")
    assert s["status"]["phase"] == "Failed"
    assert s["status"]["trialsFailed"] == 2


def test_invalid_study_spec_fails_fast():
    client = FakeKubeClient()
    ctrl = StudyController(client)
    client.create({
        "apiVersion": STUDY_API_VERSION, "kind": STUDY_KIND,
        "metadata": {"name": "bad", "namespace": "default"},
        "spec": {"objective": {"metric": "m"}, "parameters": []},
    })
    assert ctrl.reconcile("default", "bad") is None
    s = client.get(STUDY_API_VERSION, STUDY_KIND, "default", "bad")
    assert s["status"]["phase"] == "Failed"
    assert "invalid spec" in s["status"]["message"]


def test_studyspec_validation():
    with pytest.raises(ValueError):
        StudySpec.from_dict({"objective": {"metric": "m", "type": "upward"},
                             "parameters": [{"name": "x", "type": "double",
                                             "min": 0, "max": 1}],
                             "trialTemplate": {"image": "i"}})


def test_studyspec_goal_coercion():
    base = {"objective": {"metric": "m", "goal": "0.5"},
            "parameters": [{"name": "x", "type": "double",
                            "min": 0, "max": 1}],
            "trialTemplate": {"image": "i"}}
    assert StudySpec.from_dict(base).goal == 0.5  # YAML string coerced
    base["objective"]["goal"] = "not-a-number"
    with pytest.raises(ValueError):
        StudySpec.from_dict(base)


def test_study_controller_provisions_metrics_rbac(client=None):
    client = FakeKubeClient()
    ctrl = StudyController(client)
    client.create(study("s", "team-a", _study_spec()))
    ctrl.reconcile("team-a", "s")
    role = client.get("rbac.authorization.k8s.io/v1", "Role", "team-a",
                      "trial-metrics-writer")
    assert role["rules"][0]["resources"] == ["configmaps"]
    rb = client.get("rbac.authorization.k8s.io/v1", "RoleBinding", "team-a",
                    "trial-metrics-writer")
    assert rb["subjects"][0]["name"] == "default"


def test_study_terminates_when_grid_exhausted():
    # grid has only 3 combos < maxTrials=6: the study must still terminate
    client = FakeKubeClient()
    ctrl = StudyController(client)
    spec = _study_spec(
        algorithm={"name": "grid"},
        parameters=[{"name": "lr", "type": "discrete",
                     "values": [0.1, 0.2, 0.3]}],
        maxTrials=6)
    client.create(study("s", "default", spec))
    s = _run_study(client, ctrl)
    assert s["status"]["phase"] == "Succeeded"
    assert s["status"]["trials"] == 3


def test_hyperband_fills_rung_after_failures():
    space = SearchSpace.from_dicts([
        {"name": "x", "type": "double", "min": 0.0, "max": 1.0},
    ])
    hb = Hyperband(space, settings={"resource": "steps", "max_resource": 9})
    sched = hb.schedule()
    n0, n1 = sched[0][0]["n"], sched[0][1]["n"]
    rung0 = hb.suggest([], n0)
    # almost everything fails: fewer survivors than rung-1 slots
    trials = [TrialRecord(rung0[0], rung0[0]["x"])]
    trials += [TrialRecord(p, None, failed=True) for p in rung0[1:]]
    rung1 = hb.suggest(trials, n1)
    assert len(rung1) == n1  # no deadlock: filled with fresh configs
    assert rung1[0]["x"] == rung0[0]["x"]  # sole survivor promoted first
    assert all(p["steps"] == sched[0][1]["r"] for p in rung1)


def test_unknown_algorithm_fails_study_fast():
    client = FakeKubeClient()
    ctrl = StudyController(client)
    client.create(study("s", "default", _study_spec(
        algorithm={"name": "random"})))
    # corrupt the algorithm after creation (study() validates on build)
    s = client.get(STUDY_API_VERSION, STUDY_KIND, "default", "s")
    s["spec"]["algorithm"] = {"name": "bayes"}  # typo
    client.update(s)
    assert ctrl.reconcile("default", "s") is None
    s = client.get(STUDY_API_VERSION, STUDY_KIND, "default", "s")
    assert s["status"]["phase"] == "Failed"
    assert "bayes" in s["status"]["message"]


def test_goal_kills_inflight_trials():
    client = FakeKubeClient()
    ctrl = StudyController(client)
    spec = _study_spec(objective={"type": "maximize", "metric": "accuracy",
                                  "goal": 0.5},
                       parallelTrials=3, maxTrials=30)
    client.create(study("s", "default", spec))
    ctrl.reconcile("default", "s")
    jobs = client.list(TPUJOB_API_VERSION, TPUJOB_KIND, "default")
    assert len(jobs) == 3
    # only the first trial finishes, meeting the goal
    first = jobs[0]["metadata"]["name"]
    report_trial_metrics(client, "default", first, {"accuracy": 0.9})
    jobs[0].setdefault("status", {})["phase"] = "Succeeded"
    client.update_status(jobs[0])
    ctrl.reconcile("default", "s")
    s = client.get(STUDY_API_VERSION, STUDY_KIND, "default", "s")
    assert s["status"]["phase"] == "Succeeded"
    # the two in-flight jobs were torn down, their trials marked Killed
    remaining = client.list(TPUJOB_API_VERSION, TPUJOB_KIND, "default")
    assert [j["metadata"]["name"] for j in remaining] == [first]
    killed = [t for t in client.list(STUDY_API_VERSION, TRIAL_KIND, "default")
              if t.get("status", {}).get("phase") == "Killed"]
    assert len(killed) == 2


def test_orphan_trial_job_is_repaired():
    client = FakeKubeClient()
    ctrl = StudyController(client)
    client.create(study("s", "default", _study_spec(parallelTrials=1)))
    ctrl.reconcile("default", "s")
    # simulate a crash between trial create and job create
    trial_name = client.list(
        STUDY_API_VERSION, TRIAL_KIND, "default")[0]["metadata"]["name"]
    client.delete(TPUJOB_API_VERSION, TPUJOB_KIND, "default", trial_name)
    ctrl.reconcile("default", "s")
    job = client.get(TPUJOB_API_VERSION, TPUJOB_KIND, "default", trial_name)
    assert job["spec"]["env"]["KFTPU_TRIAL_NAME"] == trial_name


def test_spawn_rolls_back_trial_on_foreign_job_collision():
    client = FakeKubeClient()
    ctrl = StudyController(client)
    # a pre-existing foreign TpuJob occupies the first trial's name
    from kubeflow_tpu.operators.tpujob import tpujob

    client.create(tpujob("s-t0", "default", {"image": "other:latest"}))
    client.create(study("s", "default", _study_spec(parallelTrials=2)))
    ctrl.reconcile("default", "s")
    trials = client.list(STUDY_API_VERSION, TRIAL_KIND, "default")
    # the colliding trial was rolled back, not left as a Pending orphan
    assert all(t["metadata"]["name"] != "s-t0" for t in trials)
    assert len(trials) == 1  # the non-colliding slot proceeded


# -- suggestion service ----------------------------------------------------

def test_suggestion_service_handler():
    out = handle_suggest({
        "algorithm": "grid",
        "parameters": [{"name": "x", "type": "discrete", "values": [1, 2]}],
        "count": 5,
    })
    assert [a["x"] for a in out["assignments"]] == [1, 2]


def test_suggestion_service_http_roundtrip():
    srv = serve(port=0, background=True)
    port = srv.server_address[1]
    body = json.dumps({
        "algorithm": "random", "count": 2, "seed": 1,
        "parameters": [{"name": "lr", "type": "double",
                        "min": 0.0, "max": 1.0}],
    }).encode()
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/suggest", data=body,
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=5) as resp:
        out = json.loads(resp.read())
    assert len(out["assignments"]) == 2
    assert all(0.0 <= a["lr"] <= 1.0 for a in out["assignments"])
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/healthz", timeout=5) as resp:
        health = json.loads(resp.read())
    assert health["ok"] and "bayesian" in health["algorithms"]
    srv.shutdown()


# -- manifests -------------------------------------------------------------

def test_tuning_component_manifests():
    config = DeploymentConfig(name="demo")
    objs = render_component(config, ComponentSpec("tuning"))
    kinds = [(x["kind"], x["metadata"]["name"]) for x in objs]
    assert ("CustomResourceDefinition", "studies.kubeflow-tpu.org") in kinds
    assert ("CustomResourceDefinition", "trials.kubeflow-tpu.org") in kinds
    assert ("Deployment", "study-controller") in kinds
    assert ("Role", "trial-metrics-writer") in kinds
    assert ("RoleBinding", "trial-metrics-writer") in kinds
    for algo in ("random", "grid", "bayesian", "hyperband"):
        assert ("Deployment", f"suggestion-{algo}") in kinds
        assert ("Service", f"suggestion-{algo}") in kinds


# -- early stopping (katib earlystopping parity) ----------------------------

def test_median_early_stopping_kills_lagging_trial():
    """Three completed trials with good step histories; a running trial
    whose curve is clearly worse gets killed at the median rule, keeps its
    best-so-far observation, and does NOT get its job resurrected."""
    from kubeflow_tpu.tuning.study import append_trial_history

    client = FakeKubeClient()
    ctrl = StudyController(client)
    client.create(study("s", "default", _study_spec(
        parallelTrials=4, maxTrials=8,
        earlyStopping={"name": "median",
                       "settings": {"minTrials": 3, "minSteps": 2}})))
    ctrl.reconcile("default", "s")  # spawns 4 trials
    trials = client.list(STUDY_API_VERSION, TRIAL_KIND, "default")
    assert len(trials) == 4
    names = [t["metadata"]["name"] for t in trials]
    # three finish with strong histories
    for tname in names[:3]:
        for step, v in ((1, 0.5), (2, 0.7), (3, 0.8)):
            append_trial_history(client, "default", tname, step, v)
        report_trial_metrics(client, "default", tname, {"accuracy": 0.8})
        job = client.get(TPUJOB_API_VERSION, TPUJOB_KIND, "default", tname)
        job.setdefault("status", {})["phase"] = "Succeeded"
        client.update_status(job)
    # the fourth runs with a clearly-worse curve
    lag = names[3]
    job = client.get(TPUJOB_API_VERSION, TPUJOB_KIND, "default", lag)
    job.setdefault("status", {})["phase"] = "Running"
    client.update_status(job)
    for step, v in ((1, 0.1), (2, 0.15), (3, 0.2)):
        append_trial_history(client, "default", lag, step, v)

    ctrl.reconcile("default", "s")
    t = client.get(STUDY_API_VERSION, TRIAL_KIND, "default", lag)
    assert t["status"]["phase"] == "EarlyStopped"
    assert t["status"]["observation"]["accuracy"] == pytest.approx(0.2)
    assert client.get_or_none(TPUJOB_API_VERSION, TPUJOB_KIND, "default",
                              lag) is None
    s = client.get(STUDY_API_VERSION, STUDY_KIND, "default", "s")
    assert s["status"]["trialsEarlyStopped"] == 1

    # next pass: the stopped trial's job must NOT be recreated
    ctrl.reconcile("default", "s")
    assert client.get_or_none(TPUJOB_API_VERSION, TPUJOB_KIND, "default",
                              lag) is None


def test_median_early_stopping_needs_min_trials():
    """With fewer completed peers than minTrials, nothing is stopped."""
    from kubeflow_tpu.tuning.study import append_trial_history

    client = FakeKubeClient()
    ctrl = StudyController(client)
    client.create(study("s", "default", _study_spec(
        parallelTrials=2, earlyStopping={"name": "median",
                                         "settings": {"minTrials": 3}})))
    ctrl.reconcile("default", "s")
    names = [t["metadata"]["name"]
             for t in client.list(STUDY_API_VERSION, TRIAL_KIND, "default")]
    for tname in names:
        job = client.get(TPUJOB_API_VERSION, TPUJOB_KIND, "default", tname)
        job.setdefault("status", {})["phase"] = "Running"
        client.update_status(job)
        append_trial_history(client, "default", tname, 1, 0.01)
    ctrl.reconcile("default", "s")
    for tname in names:
        t = client.get(STUDY_API_VERSION, TRIAL_KIND, "default", tname)
        assert t["status"].get("phase") != "EarlyStopped"


def test_studyspec_rejects_unknown_early_stopping():
    with pytest.raises(ValueError, match="earlyStopping"):
        StudySpec.from_dict(_study_spec(earlyStopping={"name": "bogus"}))


def test_trial_history_roundtrip():
    from kubeflow_tpu.tuning.study import (
        append_trial_history,
        read_trial_history,
        read_trial_metrics,
    )

    client = FakeKubeClient()
    append_trial_history(client, "default", "t1", 1, 0.5)
    append_trial_history(client, "default", "t1", 2, 0.75)
    assert read_trial_history(client, "default", "t1") == [(1, 0.5),
                                                           (2, 0.75)]
    # final metrics live in the same ConfigMap, history key excluded
    report_trial_metrics(client, "default", "t1", {"accuracy": 0.9})
    assert read_trial_metrics(client, "default", "t1") == {"accuracy": 0.9}
    assert read_trial_history(client, "default", "t1") == [(1, 0.5),
                                                           (2, 0.75)]


def test_report_tuning_metrics_hook(monkeypatch):
    """The launcher hook publishes history + finals under the trial env
    contract and is a no-op outside a study."""
    from kubeflow_tpu.examples.common import report_tuning_metrics
    from kubeflow_tpu.tuning.study import (
        read_trial_history,
        read_trial_metrics,
    )

    client = FakeKubeClient()
    # outside a study: nothing happens, nothing raises
    report_tuning_metrics(1, {"accuracy": 0.5}, client=client)

    monkeypatch.setenv("KFTPU_TRIAL_NAME", "s-t0")
    monkeypatch.setenv("KFTPU_NAMESPACE", "default")
    monkeypatch.setenv("KFTPU_OBJECTIVE_METRIC", "accuracy")
    report_tuning_metrics(1, {"accuracy": 0.5, "loss": 2.0}, client=client)
    report_tuning_metrics(2, {"accuracy": 0.7, "loss": 1.0}, client=client,
                          final=True)
    assert read_trial_history(client, "default", "s-t0") == [(1, 0.5),
                                                             (2, 0.7)]
    finals = read_trial_metrics(client, "default", "s-t0")
    assert finals == {"accuracy": 0.7, "loss": 1.0}
