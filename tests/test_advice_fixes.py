"""Regression tests for the round-1 advisor findings (ADVICE.md)."""

import calendar
import json
import urllib.request

import pytest

from kubeflow_tpu.k8s import FakeKubeClient
from kubeflow_tpu.k8s import objects as o


# -- 1. traffic split pins each backend to its own model version ------------


def test_traffic_split_deployments_pin_version():
    from kubeflow_tpu.config.deployment import DeploymentConfig
    from kubeflow_tpu.manifests.components.serving import render

    config = DeploymentConfig(name="d", namespace="kf")
    objs = render(config, {
        **__import__("kubeflow_tpu.manifests.components.serving",
                     fromlist=["DEFAULTS"]).DEFAULTS,
        "traffic_split": {"v1": 90, "v2": 10},
    })
    deploys = {obj["metadata"]["name"]: obj for obj in objs
               if obj["kind"] == "Deployment"}
    for version in ("v1", "v2"):
        ctr = (deploys[f"model-server-{version}"]["spec"]["template"]["spec"]
               ["containers"][0])
        env = {e["name"]: e["value"] for e in ctr["env"]}
        assert env["KFTPU_MODEL_VERSION"] == version


def test_parse_pin_version():
    from kubeflow_tpu.serving.server import parse_pin_version

    assert parse_pin_version(None) is None
    assert parse_pin_version("") is None
    assert parse_pin_version("3") == 3
    assert parse_pin_version("v7") == 7
    with pytest.raises(ValueError):
        parse_pin_version("latest")


def test_pinned_repository_serves_pinned_not_latest(tmp_path):
    import jax
    import jax.numpy as jnp
    import numpy as np

    from kubeflow_tpu.models import MnistCnn
    from kubeflow_tpu.serving import ModelServer, export_model

    model = MnistCnn()
    params = model.init(jax.random.key(0),
                        jnp.zeros((1, 28, 28, 1)))["params"]
    export_model(str(tmp_path / "mnist"), "mnist", params, version=1)
    zero = jax.tree_util.tree_map(jnp.zeros_like, params)
    export_model(str(tmp_path / "mnist"), "mnist", zero, version=2)

    pinned = ModelServer(str(tmp_path), port=0, pin_version=1)
    assert pinned.repo.get("mnist").version == 1
    latest = ModelServer(str(tmp_path), port=0)
    assert latest.repo.get("mnist").version == 2
    # pinned output matches the v1 params, not the zeroed v2 params
    x = jnp.ones((1, 28, 28, 1))
    np.testing.assert_allclose(
        np.asarray(pinned.repo.get("mnist").predict(x)),
        np.asarray(model.apply({"params": params}, x)), atol=1e-5)


def test_pinned_repository_waits_for_absent_version(tmp_path):
    import jax
    import jax.numpy as jnp

    from kubeflow_tpu.models import MnistCnn
    from kubeflow_tpu.serving import ModelServer, export_model

    model = MnistCnn()
    params = model.init(jax.random.key(0),
                        jnp.zeros((1, 28, 28, 1)))["params"]
    export_model(str(tmp_path / "mnist"), "mnist", params, version=1)
    server = ModelServer(str(tmp_path), port=0, pin_version=5)
    assert server.repo.get("mnist") is None
    export_model(str(tmp_path / "mnist"), "mnist", params, version=5)
    server.repo.refresh()
    assert server.repo.get("mnist").version == 5


# -- 2. kubebench DAG rides a shared experiment PVC -------------------------


def test_benchmark_workflow_mounts_experiment_pvc():
    from kubeflow_tpu.bench.kubebench import benchmark_workflow

    wf = benchmark_workflow(
        "exp", "kf",
        job_spec={"image": "img"},
        post_job={"image": "post"},
        experiment_pvc="exp-pvc",
    )
    steps = {s["name"]: s for s in wf["spec"]["steps"]}
    job_spec = steps["launch-main-job"]["manifest"]["spec"]
    assert job_spec["volumes"][0]["persistentVolumeClaim"]["claimName"] == \
        "exp-pvc"
    assert job_spec["volumeMounts"][0]["mountPath"] == "/results"
    for step_name in ("run-post-job", "run-reporter"):
        step = steps[step_name]
        assert step["volumes"][0]["persistentVolumeClaim"]["claimName"] == \
            "exp-pvc"
        assert step["volumeMounts"][0]["mountPath"] == "/results"


def test_tpujob_worker_pod_carries_volumes():
    from kubeflow_tpu.operators.tpujob import build_worker_pod, tpujob
    from kubeflow_tpu.scheduler.placement import SlicePlacement

    job = tpujob("j", "kf", {
        "image": "img",
        "volumes": [{"name": "exp",
                     "persistentVolumeClaim": {"claimName": "exp-pvc"}}],
        "volumeMounts": [{"name": "exp", "mountPath": "/results"}],
    })
    pod = build_worker_pod(
        job, 0, SlicePlacement(slice_index=0, host=0, topology="2x4",
                               accelerator="tpu-v5-lite-podslice"))
    spec = pod["spec"]
    assert spec["volumes"][0]["persistentVolumeClaim"]["claimName"] == \
        "exp-pvc"
    assert spec["containers"][0]["volumeMounts"][0]["mountPath"] == "/results"


def test_workflow_controller_renders_step_volumes(tmp_path):
    from kubeflow_tpu.workflows.controller import WorkflowController
    from kubeflow_tpu.workflows.workflow import container_step, workflow

    client = FakeKubeClient()
    ctrl = WorkflowController(client)
    wf = workflow("w", "kf", [container_step(
        "s", "img",
        volumes=[{"name": "v", "emptyDir": {}}],
        volume_mounts=[{"name": "v", "mountPath": "/data"}])])
    client.create(wf)
    ctrl.reconcile("kf", "w")
    pods = client.list("v1", "Pod", "kf")
    assert len(pods) == 1
    spec = pods[0]["spec"]
    assert spec["volumes"] == [{"name": "v", "emptyDir": {}}]
    assert spec["containers"][0]["volumeMounts"] == [
        {"name": "v", "mountPath": "/data"}]


# -- 3. header-trusting services sit behind cookie auth / NetworkPolicy -----


def _request(url, method="GET", headers=None):
    req = urllib.request.Request(url, method=method,
                                 headers=dict(headers or {}))
    try:
        with urllib.request.urlopen(req, timeout=10) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read() or b"{}")


def test_serve_json_authenticator_rejects_and_overrides_header():
    from kubeflow_tpu.auth.gatekeeper import AuthServer, cookie_authenticator
    from kubeflow_tpu.utils.jsonhttp import serve_json

    secret = b"test-secret"
    issuer = AuthServer({}, secret)
    seen = {}

    def handle(method, path, body, user):
        seen["user"] = user
        return 200, {"user": user}

    srv = serve_json(handle, 0, background=True, host="127.0.0.1",
                     authenticator=cookie_authenticator(secret))
    try:
        port = srv.server_address[1]
        base = f"http://127.0.0.1:{port}"
        # no cookie → 401 even with a spoofed identity header
        code, _ = _request(base + "/x",
                           headers={"X-Kubeflow-Userid": "admin"})
        assert code == 401
        assert "user" not in seen
        # valid cookie → the cookie's identity wins over the spoofed header
        cookie = issuer.issue_cookie("alice")
        code, payload = _request(
            base + "/x",
            headers={"X-Kubeflow-Userid": "admin",
                     "Cookie": f"kftpu-auth={cookie}"})
        assert code == 200
        assert payload["user"] == "alice"
    finally:
        srv.shutdown()


def test_authenticator_from_env(monkeypatch):
    from kubeflow_tpu.auth.gatekeeper import authenticator_from_env

    monkeypatch.delenv("KFTPU_AUTH_SECRET", raising=False)
    assert authenticator_from_env() is None
    monkeypatch.setenv("KFTPU_AUTH_SECRET", "s3cret")
    auth = authenticator_from_env()
    assert auth is not None
    assert auth({}) is None  # no cookie → reject


def test_web_components_render_network_policies():
    from kubeflow_tpu.config.deployment import ComponentSpec, DeploymentConfig
    from kubeflow_tpu.manifests import components  # noqa: F401 — registers
    from kubeflow_tpu.manifests.registry import render_component

    config = DeploymentConfig(name="d", namespace="kf")
    for component, app in (("dashboard", "centraldashboard"),
                           ("notebooks", "notebook-webapp"),
                           ("tenancy", "kfam")):
        objs = render_component(config, ComponentSpec(name=component))
        nps = [obj for obj in objs if obj["kind"] == "NetworkPolicy"]
        assert nps, f"{component} renders no NetworkPolicy"
        np_obj = nps[0]
        assert np_obj["spec"]["podSelector"]["matchLabels"]["app"] == app
        peers = np_obj["spec"]["ingress"][0]["from"]
        assert {"podSelector": {"matchLabels":
                                {"app": "kftpu-ingressgateway"}}} in peers


# -- 4. cron catch-up of missed runs ----------------------------------------


def test_cron_catches_up_missed_run():
    from kubeflow_tpu.workflows.cron import ScheduledWorkflowController
    from kubeflow_tpu.workflows.cron import scheduled_workflow
    from kubeflow_tpu.workflows.workflow import (
        WORKFLOW_API_VERSION,
        WORKFLOW_KIND,
        container_step,
    )

    client = FakeKubeClient()
    base = calendar.timegm((2026, 7, 29, 3, 0, 10, 0, 0, 0))  # 03:00:10
    now = [float(base)]
    ctrl = ScheduledWorkflowController(client, clock=lambda: now[0])
    client.create(scheduled_workflow(
        "hourly", "default",
        {"steps": [container_step("s", "img")]},
        cron="0 * * * *"))
    ctrl.reconcile("default", "hourly")
    assert len(client.list(WORKFLOW_API_VERSION, WORKFLOW_KIND,
                           "default")) == 1
    # the controller sleeps through 04:00 and reconciles at 04:01:30 —
    # the missed run must fire (the old matches(now)-only rule skipped it)
    now[0] = float(base + 3600 + 80)
    ctrl.reconcile("default", "hourly")
    assert len(client.list(WORKFLOW_API_VERSION, WORKFLOW_KIND,
                           "default")) == 2


def test_cron_skips_misses_beyond_backfill_window():
    from kubeflow_tpu.workflows.cron import ScheduledWorkflowController
    from kubeflow_tpu.workflows.cron import scheduled_workflow
    from kubeflow_tpu.workflows.workflow import (
        WORKFLOW_API_VERSION,
        WORKFLOW_KIND,
        container_step,
    )

    client = FakeKubeClient()
    base = calendar.timegm((2026, 7, 29, 3, 0, 10, 0, 0, 0))
    now = [float(base)]
    ctrl = ScheduledWorkflowController(client, clock=lambda: now[0])
    swf = scheduled_workflow(
        "hourly", "default",
        {"steps": [container_step("s", "img")]},
        cron="0 * * * *")
    swf["spec"]["catchUpWindowSeconds"] = 90
    client.create(swf)
    ctrl.reconcile("default", "hourly")
    # down for 3 hours, reconciling at 06:05: the most recent miss (06:00)
    # is older than the 90s window → skip, don't backfill
    now[0] = float(base + 3 * 3600 + 290)
    ctrl.reconcile("default", "hourly")
    assert len(client.list(WORKFLOW_API_VERSION, WORKFLOW_KIND,
                           "default")) == 1
    # next live match still fires
    now[0] = float(base + 4 * 3600 - 8)  # 07:00:02
    ctrl.reconcile("default", "hourly")
    assert len(client.list(WORKFLOW_API_VERSION, WORKFLOW_KIND,
                           "default")) == 2


def test_cron_recent_miss_fires_despite_old_misses():
    # CronJob startingDeadlineSeconds parity: an out-of-window OLD miss must
    # not mask a fresh in-window one
    from kubeflow_tpu.workflows.cron import ScheduledWorkflowController
    from kubeflow_tpu.workflows.cron import scheduled_workflow
    from kubeflow_tpu.workflows.workflow import (
        WORKFLOW_API_VERSION,
        WORKFLOW_KIND,
        container_step,
    )

    client = FakeKubeClient()
    base = calendar.timegm((2026, 7, 29, 3, 0, 10, 0, 0, 0))
    now = [float(base)]
    ctrl = ScheduledWorkflowController(client, clock=lambda: now[0])
    swf = scheduled_workflow(
        "hourly", "default",
        {"steps": [container_step("s", "img")]},
        cron="0 * * * *")
    swf["spec"]["catchUpWindowSeconds"] = 600
    client.create(swf)
    ctrl.reconcile("default", "hourly")
    # down through 04:00 and 05:00, back at 06:02 — 06:00 is within the
    # window and must fire even though 04:00/05:00 are beyond it
    now[0] = float(base + 3 * 3600 + 110)
    ctrl.reconcile("default", "hourly")
    assert len(client.list(WORKFLOW_API_VERSION, WORKFLOW_KIND,
                           "default")) == 2


# -- 5. hyperband records stay slot-aligned after a trial deletion ----------


def test_records_fill_deleted_trial_slots():
    from kubeflow_tpu.tuning.controller import StudyController
    from kubeflow_tpu.tuning.study import STUDY_API_VERSION, TRIAL_KIND
    from kubeflow_tpu.tuning.study import StudySpec

    client = FakeKubeClient()
    ctrl = StudyController(client)
    spec = StudySpec.from_dict({
        "objective": {"metric": "acc", "type": "maximize"},
        "parameters": [
            {"name": "lr", "type": "double", "min": 0.001, "max": 0.1}],
        "trialTemplate": {"image": "img"},
    })

    def trial_obj(index, acc=None):
        t = {
            "apiVersion": STUDY_API_VERSION,
            "kind": TRIAL_KIND,
            "metadata": {"name": f"s-t{index}", "namespace": "d"},
            "spec": {"index": index, "parameters": {"lr": 0.01 * (index + 1)}},
            "status": {},
        }
        if acc is not None:
            t["status"] = {"phase": "Succeeded", "observation": {"acc": acc}}
        return t

    # trial 1 was rolled back (name collision) — a hole in the index space
    trials = [trial_obj(0, acc=0.5), trial_obj(2, acc=0.9)]
    recs = ctrl._records(spec, trials)
    assert len(recs) == 3
    assert recs[0].objective == 0.5
    assert recs[1].failed and recs[1].objective is None  # placeholder
    assert recs[2].objective == 0.9
