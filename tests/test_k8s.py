"""Fake API server + apply engine tests."""

import pytest

from kubeflow_tpu.k8s import ApiError, FakeKubeClient, objects as o
from kubeflow_tpu.k8s.apply import apply_all, delete_all, prune, sort_for_apply


@pytest.fixture
def client():
    return FakeKubeClient()


def test_create_get_roundtrip(client):
    cm = o.config_map("cfg", "ns1", {"a": "1"})
    created = client.create(cm)
    assert created["metadata"]["uid"].startswith("uid-")
    got = client.get("v1", "ConfigMap", "ns1", "cfg")
    assert got["data"] == {"a": "1"}


def test_create_conflict(client):
    cm = o.config_map("cfg", "ns1", {"a": "1"})
    client.create(cm)
    with pytest.raises(ApiError) as ei:
        client.create(cm)
    assert ei.value.code == 409


def test_get_missing_404(client):
    with pytest.raises(ApiError) as ei:
        client.get("v1", "ConfigMap", "ns1", "nope")
    assert ei.value.code == 404


def test_list_with_label_selector(client):
    client.create(o.service("a", "ns1", {"app": "x"}, [{"port": 80}],
                            labels={"team": "ml"}))
    client.create(o.service("b", "ns1", {"app": "y"}, [{"port": 80}],
                            labels={"team": "web"}))
    got = client.list("v1", "Service", "ns1", label_selector={"team": "ml"})
    assert [g["metadata"]["name"] for g in got] == ["a"]


def test_update_bumps_resource_version(client):
    cm = client.create(o.config_map("cfg", "ns1", {"a": "1"}))
    rv1 = cm["metadata"]["resourceVersion"]
    cm["data"]["a"] = "2"
    updated = client.update(cm)
    assert updated["metadata"]["resourceVersion"] != rv1
    assert client.get("v1", "ConfigMap", "ns1", "cfg")["data"]["a"] == "2"


def test_update_status_subresource_only_touches_status(client):
    job = {"apiVersion": "kubeflow-tpu.org/v1alpha1", "kind": "TpuJob",
           "metadata": {"name": "j", "namespace": "ns1"},
           "spec": {"slices": 1}}
    client.create(job)
    client.update_status({**job, "spec": {"slices": 99},
                          "status": {"phase": "Running"}})
    got = client.get("kubeflow-tpu.org/v1alpha1", "TpuJob", "ns1", "j")
    assert got["status"]["phase"] == "Running"
    assert got["spec"]["slices"] == 1  # spec change via status endpoint ignored


def test_watch_replays_and_streams(client):
    client.create(o.config_map("pre", "ns1", {}))
    q = client.watch("v1", "ConfigMap", "ns1")
    evt = q.get_nowait()
    assert evt.type == "ADDED" and evt.object["metadata"]["name"] == "pre"
    client.create(o.config_map("post", "ns1", {}))
    evt = q.get_nowait()
    assert evt.type == "ADDED" and evt.object["metadata"]["name"] == "post"
    client.delete("v1", "ConfigMap", "ns1", "post")
    assert q.get_nowait().type == "DELETED"


def test_owner_reference_cascade_delete(client):
    owner = client.create({"apiVersion": "kubeflow-tpu.org/v1alpha1",
                           "kind": "TpuJob",
                           "metadata": {"name": "j", "namespace": "ns1"}})
    child = o.pod("j-worker-0", "ns1", o.pod_spec([o.container("c", "img")]))
    o.set_owner(child, owner)
    client.create(child)
    client.delete("kubeflow-tpu.org/v1alpha1", "TpuJob", "ns1", "j")
    assert client.get_or_none("v1", "Pod", "ns1", "j-worker-0") is None


def test_sort_for_apply_order():
    objs = [
        o.deployment("d", "ns", o.pod_spec([o.container("c", "i")])),
        o.namespace("ns"),
        o.crd("things", "g.io", "Thing"),
        o.service_account("sa", "ns"),
    ]
    kinds = [x["kind"] for x in sort_for_apply(objs)]
    assert kinds == ["CustomResourceDefinition", "Namespace", "ServiceAccount",
                     "Deployment"]


def test_apply_all_is_idempotent(client):
    objs = [o.namespace("ns1"), o.config_map("cfg", "ns1", {"a": "1"})]
    apply_all(client, objs)
    apply_all(client, objs)  # second run updates, no conflict
    assert len(client.list("v1", "ConfigMap", "ns1")) == 1


def test_apply_all_retries_with_injected_sleep(client):
    """The retry/backoff path on the injectable Sleep contract (TPU003):
    two transient failures then success — deterministic, no real sleep,
    exponential delays observed."""
    cm = o.config_map("cfg", "ns1", {"a": "1"})
    fails = {"n": 2}
    real_apply = client.apply

    def flaky_apply(obj):
        if fails["n"] > 0:
            fails["n"] -= 1
            raise ApiError(500, "transient")
        return real_apply(obj)

    client.apply = flaky_apply
    slept = []
    applied = apply_all(client, [cm], retries=3, backoff_s=2.0,
                        sleep=slept.append)
    assert [a["metadata"]["name"] for a in applied] == ["cfg"]
    assert slept == [2.0, 4.0]  # backoff_s * 2**attempt, no final sleep


def test_apply_all_raises_after_exhausted_retries_without_final_sleep(client):
    cm = o.config_map("cfg", "ns1", {"a": "1"})

    def always_fails(obj):
        raise ApiError(500, "down")

    client.apply = always_fails
    slept = []
    with pytest.raises(ApiError):
        apply_all(client, [cm], retries=3, backoff_s=1.0,
                  sleep=slept.append)
    assert slept == [1.0, 2.0]  # no sleep after the final attempt


def test_delete_all_ignores_missing(client):
    objs = [o.config_map("cfg", "ns1", {})]
    apply_all(client, objs)
    delete_all(client, objs)
    delete_all(client, objs)  # already gone: no raise


def test_prune_removes_undesired(client):
    a = o.config_map("a", "ns1", {})
    b = o.config_map("b", "ns1", {})
    apply_all(client, [a, b])
    pruned = prune(client, desired=[a], observed=client.list("v1", "ConfigMap", "ns1"))
    assert [p["metadata"]["name"] for p in pruned] == ["b"]
    assert client.get_or_none("v1", "ConfigMap", "ns1", "b") is None
