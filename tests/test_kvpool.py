"""Host-side page-allocator invariants that previously lived only in
docstrings, now test-gated:

- **writable exclusivity** — no page is ever writable by two slots, and
  any page mapped by several owners (slots/store) is read-only for all
  but its allocator, across admit / grow / share / COW-split / evict
  sequences (``PagePool.check_invariants`` verifies the full ownership
  model: refcount == table references + store pins, free-list
  consistency, single-writer);
- **prefix-trie semantics** — page-granular chain matching (partial
  hits the exact-key store missed), idempotent store, leaf-first LRU
  eviction, COW tails;
- **eviction pressure racing a COW split** — a store entry evicted
  between the trie match and the split must not free the boundary page
  out from under the placement (``map_cow``'s ref holds it).
"""

import numpy as np
import pytest

from kubeflow_tpu.serving.kvpool import (
    OutOfPages,
    PagePool,
    PrefixPageStore,
)


def _pool(pages=16, ps=4, slots=4, per_slot=8):
    return PagePool(pages, ps, slots, per_slot)


def _toks(*vals):
    return np.asarray(vals, np.int32)


def _page_tokens(n_pages, ps=4, base=1):
    return np.arange(base, base + n_pages * ps, dtype=np.int32)


# -- PagePool ownership model ------------------------------------------------


def test_slot_lifecycle_invariants_every_step():
    pool = _pool()
    pool.check_invariants()
    pool.reserve(0, 4)
    pool.check_invariants()
    pool.ensure(0, 9)            # 3 pages for 9 tokens (ps=4)
    pool.check_invariants()
    assert pool.pages_in_use == 3 and pool._slot[0].reserved == 1
    assert all(pool.writer_of(int(p)) == 0
               for p in pool.tables[0, :3])
    pool.ensure(0, 16)           # grow draws the reservation down
    pool.check_invariants()
    with pytest.raises(OutOfPages):
        pool.alloc(0, 5)         # reservation exhausted
    pool.release_slot(0)
    pool.check_invariants()
    pool.check_idle()


def test_shared_pages_are_read_only_for_sharers():
    pool = _pool()
    pool.reserve(0, 2)
    pool.ensure(0, 8)
    store_pages = [pool.pin_one(0, 0), pool.pin_one(0, 1)]
    pool.check_invariants()
    # a second slot maps the shared pages: ref 3, still ONE writer
    pool.reserve(1, 2)
    for logical, p in enumerate(store_pages):
        pool.map_shared(1, logical, p)
    pool.check_invariants()
    assert pool.ref[store_pages[0]] == 3
    assert pool.writer_of(store_pages[0]) == 0
    assert store_pages[0] not in pool._slot[1].owned
    # writer retires: pages survive (store + sharer), no writer at all
    pool.release_slot(0)
    pool.check_invariants()
    assert pool.writer_of(store_pages[0]) is None
    pool.release_slot(1)
    pool.unpin(store_pages)
    pool.check_idle()


def test_cow_split_bookkeeping():
    pool = _pool()
    pool.reserve(0, 1)
    pool.ensure(0, 3)
    boundary = pool.pin_one(0, 0)
    pool.release_slot(0)         # only the store pin remains
    pool.check_invariants()
    pool.reserve(1, 2)           # 1 for the split + 1 to grow
    pool.map_cow(1, 0, boundary)
    pool.check_invariants()
    assert pool.writer_of(boundary) is None      # read-only share
    src, dst = pool.cow_split(1, 0)
    pool.check_invariants()
    assert src == boundary and dst != boundary
    assert pool.writer_of(dst) == 1
    assert pool.tables[1, 0] == dst
    assert pool.ref[boundary] == 1               # back to store-only
    assert pool.cow_splits == 1
    pool.release_slot(1)
    pool.unpin([boundary])
    pool.check_idle()


def test_random_walk_never_double_writes(seed=3):
    """Property walk: random admit/grow/share/COW-split/retire/pin/
    unpin sequences keep the full ownership model intact at every
    step. The deterministic free list makes failures replayable."""
    rng = np.random.default_rng(seed)
    pool = _pool(pages=24, ps=4, slots=6, per_slot=6)
    live = {}       # slot -> tokens grown so far
    pins = []       # store-pinned (page, from_slot)
    cows = {}       # slot -> logical mapped COW
    for step in range(400):
        op = rng.integers(0, 6)
        slot = int(rng.integers(0, 6))
        if op == 0 and slot not in live:           # admit
            need = int(rng.integers(1, 5))
            if pool.can_reserve(need):
                pool.reserve(slot, need)
                live[slot] = 0
        elif op == 1 and slot in live:             # grow
            want = live[slot] + int(rng.integers(1, 8))
            if (pool.pages_needed(want)
                    - pool.pages_needed(live[slot])
                    <= pool._slot[slot].reserved):
                pool.ensure(slot, want)
                live[slot] = want
        elif op == 2 and slot in live and live[slot]:   # store-pin
            logical = int(rng.integers(
                0, pool.pages_needed(live[slot])))
            pins.append(pool.pin_one(slot, logical))
        elif op == 3 and pins and slot not in live:     # COW share
            if pool.can_reserve(1):
                pool.reserve(slot, 1)
                live[slot] = 0
                page = pins[int(rng.integers(0, len(pins)))]
                pool.map_cow(slot, 0, page)
                cows[slot] = 0
        elif op == 4 and slot in cows:             # COW split
            pool.cow_split(slot, cows.pop(slot))
            live[slot] = pool.page_size
        elif op == 5 and slot in live:             # retire
            pool.release_slot(slot)
            live.pop(slot)
            cows.pop(slot, None)
        pool.check_invariants()
    for slot in list(live):
        pool.release_slot(slot)
    pool.unpin(pins)
    pool.check_idle()


# -- PrefixPageStore: the page-granular trie ---------------------------------


def _stored_slot(pool, slot, tokens, prefix_len, store):
    """Simulate an admitted slot whose prompt pages hold ``tokens`` and
    store its prefix — the engine's placement+finalize, pool-side."""
    pool.reserve(slot, pool.pages_needed(tokens.size))
    pool.ensure(slot, tokens.size)
    store.store(tokens, prefix_len, slot)


def test_trie_partial_chain_hit_exact_store_missed():
    """THE trie acceptance shape: the old store keyed on the ENTIRE
    aligned prefix, so a request sharing only the first page(s) of a
    stored prefix shared nothing. The trie matches per page."""
    pool = _pool()
    store = PrefixPageStore(pool, budget_pages=8)
    toks = _page_tokens(3)                    # 12 tokens = 3 pages
    _stored_slot(pool, 0, toks, 12, store)
    assert store.pages_held == 3
    # same first page only — exact-key lookup of (8, bytes) would miss
    other = np.concatenate([toks[:4], _toks(90, 91, 92, 93, 94)])
    m = store.match(other, 8)
    assert m.hit and len(m.pages) == 1
    assert m.pages[0] == int(pool.tables[0, 0])
    # two shared pages out of three stored
    m2 = store.match(np.concatenate([toks[:8], _toks(77, 78, 79, 80)]),
                     12)
    assert len(m2.pages) == 2 and m2.tail_page is None
    # full chain + no tail requested
    m3 = store.match(toks, 12)
    assert len(m3.pages) == 3
    pool.release_slot(0)
    store.clear()
    pool.check_idle()


def test_trie_cow_tail_match_and_store_idempotent():
    pool = _pool()
    store = PrefixPageStore(pool, budget_pages=8)
    toks = _toks(*range(1, 11))               # 10 tokens: 2 pages + 2
    _stored_slot(pool, 0, toks, 10, store)
    assert store.pages_held == 3              # 2 nodes + 1 tail
    m = store.match(np.concatenate([toks[:10], _toks(55)]), 10)
    assert len(m.pages) == 2
    assert m.tail_page == int(pool.tables[0, 2]) and m.tail_len == 2
    # different boundary tokens: full pages hit, tail misses
    m2 = store.match(np.concatenate([toks[:8], _toks(66, 67)]), 10)
    assert len(m2.pages) == 2 and m2.tail_page is None
    # re-store is a pure LRU touch
    store.store(toks, 10, 0)
    assert store.pages_held == 3
    pool.release_slot(0)
    store.clear()
    pool.check_idle()


def test_trie_evicts_leaf_first_lru():
    pool = _pool(pages=16)
    store = PrefixPageStore(pool, budget_pages=4)
    toks = _page_tokens(2)
    _stored_slot(pool, 0, toks, 8, store)       # chain of 2
    branch = np.concatenate([toks[:4], _toks(50, 51, 52, 53, 54)])
    _stored_slot(pool, 1, branch, 9, store)     # +1 node +1 tail
    assert store.pages_held == 4
    root_page = int(pool.tables[0, 0])
    # the shared root is interior (two chains + a tail below): three
    # evictions must remove leaves before it ever becomes evictable
    for _ in range(3):
        assert store.evict_lru()
        held = set(store._held)
        assert root_page in held
        pool.check_invariants()
    assert store.evict_lru()                    # now the root leaf
    assert store.pages_held == 0
    pool.release_slot(0)
    pool.release_slot(1)
    pool.check_idle()


def test_eviction_pressure_racing_cow_split():
    """Placement takes the COW ref BEFORE reservation-driven eviction
    can run (engine `_place_paged` order). Even when the store entry is
    evicted between the match and the split — the eviction-pressure
    race — the boundary page survives on the slot's ref and the split
    copies from live content; afterwards the pool reclaims fully."""
    pool = _pool(pages=6, ps=4, slots=3, per_slot=4)
    store = PrefixPageStore(pool, budget_pages=4)
    toks = _toks(*range(1, 7))                  # 1 page + 2 boundary
    _stored_slot(pool, 0, toks, 6, store)
    pool.release_slot(0)                        # store-only now
    assert store.pages_held == 2
    m = store.match(np.concatenate([toks, _toks(88, 89)]), 6)
    assert len(m.pages) == 1 and m.tail_page is not None
    # placement: reserve, map shared+cow, THEN the store gets evicted
    # under pressure (protect excludes nothing here — worst case)
    pool.reserve(1, 2)
    pool.map_shared(1, 0, m.pages[0])
    pool.map_cow(1, 1, m.tail_page)
    while store.evict_lru():
        pass
    assert store.pages_held == 0
    pool.check_invariants()
    assert pool.ref[m.tail_page] == 1           # the slot's COW ref
    src, dst = pool.cow_split(1, 1)
    pool.check_invariants()
    assert src == m.tail_page and pool.writer_of(dst) == 1
    pool.release_slot(1)
    pool.check_idle()


def test_evict_lru_protect_skips_inflight_share():
    pool = _pool()
    store = PrefixPageStore(pool, budget_pages=8)
    a = _page_tokens(1)
    b = _page_tokens(1, base=60)
    _stored_slot(pool, 0, a, 4, store)
    _stored_slot(pool, 1, b, 4, store)
    protected = int(pool.tables[0, 0])
    assert store.evict_lru(protect={protected})
    assert protected in store._held             # the OTHER entry went
    assert not store.evict_lru(protect={protected})
    pool.release_slot(0)
    pool.release_slot(1)
    store.clear()
    pool.check_idle()


def test_store_respects_budget_and_zero_budget():
    pool = _pool(pages=16)
    disabled = PrefixPageStore(pool, budget_pages=0)
    pool.reserve(0, 3)
    pool.ensure(0, 12)
    disabled.store(_page_tokens(3), 12, 0)
    assert disabled.pages_held == 0
    small = PrefixPageStore(pool, budget_pages=2)
    small.store(_page_tokens(3), 12, 0)         # truncates at budget
    assert small.pages_held == 2
    assert len(small.match(_page_tokens(3), 12).pages) == 2
    pool.release_slot(0)
    small.clear()
    pool.check_idle()
