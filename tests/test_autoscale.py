"""TPU-slice-aware serving autoscaler (kubeflow_tpu/autoscale/).

Everything runs on a fake clock — the aggregator, recommender and
reconciler take explicit ``now`` values, so window math, panic
transitions and warmup/drain ordering are asserted deterministically.
The simulated load test at the bottom is the subsystem's acceptance
gate: burst → panic scale-up within one panic window, idle → drain +
scale-to-zero, re-arrival → held until a warmed replica admits.
"""

from typing import Dict, List

import pytest

from kubeflow_tpu.autoscale import (
    Autoscaler,
    AutoscalePolicy,
    CapacityPlanner,
    MetricsAggregator,
    Recommender,
    ReplicaDriver,
    policy_preset,
)
from kubeflow_tpu.autoscale.metrics import WindowStats
from kubeflow_tpu.scheduler.inventory import SliceInfo


def _stats(load: float, queue: float = 0.0) -> WindowStats:
    return WindowStats(concurrency=load, queue_depth=queue, rps=0.0,
                       samples=1)


def _inventory(n: int, shape: str = "v5e-4", hosts: int = 1,
               busy: int = 0) -> List[SliceInfo]:
    return [SliceInfo(slice_id=f"{shape}_{i}", shape=shape, hosts=hosts,
                      free_hosts=0 if i < busy else hosts)
            for i in range(n)]


class StubDriver(ReplicaDriver):
    """In-memory replicas with controllable warmup and drain."""

    def __init__(self) -> None:
        self.seq = 0
        self.warm: Dict[int, bool] = {}
        self.inflight: Dict[int, int] = {}
        self.log: List[str] = []
        self.instant_warm = False

    def create(self, model: str, slice_id: str) -> int:
        self.seq += 1
        self.warm[self.seq] = False
        self.inflight[self.seq] = 0
        self.log.append(f"create:{slice_id}")
        return self.seq

    def warmup(self, model: str, handle: int) -> None:
        self.log.append(f"warmup:{handle}")
        if self.instant_warm:
            self.warm[handle] = True

    def finish_warmup(self, handle: int) -> None:
        self.warm[handle] = True

    def is_warm(self, model: str, handle: int) -> bool:
        return self.warm[handle]

    def drain(self, model: str, handle: int) -> None:
        self.log.append(f"drain:{handle}")

    def in_flight(self, model: str, handle: int) -> int:
        return self.inflight[handle]

    def destroy(self, model: str, handle: int) -> None:
        self.log.append(f"destroy:{handle}")
        del self.warm[handle]


# -- metrics aggregator -----------------------------------------------------


def test_aggregator_windows_are_deterministic():
    agg = MetricsAggregator(clock=lambda: 0.0)
    # 4 requests land in second 0..3 and stay in flight
    for t in range(4):
        agg.request_start("m", now=float(t))
    assert agg.inflight("m") == 4
    # panic window (4s @ now=4) sees the ramp 1,2,3,4 → avg 2.5
    w = agg.window("m", 4.0, now=4.0)
    assert w.concurrency == pytest.approx(2.5)
    assert w.rps == pytest.approx(1.0)
    for _ in range(4):
        agg.request_finish("m", now=5.0)
    assert agg.inflight("m") == 0
    # empty window after the horizon rolls: falls back to the gauge
    w = agg.window("m", 2.0, now=60.0)
    assert w.concurrency == 0.0 and w.samples == 0


def test_aggregator_engine_occupancy_counts_as_concurrency():
    class FakeEngine:
        def snapshot(self):
            return {"active_slots": 6, "pending": 3, "slots": 8,
                    "closed": False}

    agg = MetricsAggregator(clock=lambda: 0.0)
    agg.observe_engine("m", FakeEngine(), now=1.0)
    w = agg.window("m", 10.0, now=1.0)
    assert w.concurrency == pytest.approx(6.0)
    assert w.queue_depth == pytest.approx(3.0)
    assert w.load == pytest.approx(9.0)


def test_aggregator_engine_page_pool_scales_concurrency():
    """Paged engines report token-level occupancy: 2 long-context slots
    holding 75% of the KV pool must read as 0.75 × slots concurrency
    (pages are the binding resource), while a page-idle engine keeps the
    plain slot signal — deterministic fake-clock windows both ways."""

    class LongContext:
        def snapshot(self):
            return {"active_slots": 2, "pending": 0, "slots": 8,
                    "closed": False, "paged": True,
                    "pages_total": 64, "pages_free": 16,
                    "pages_in_use": 48}

    class PageIdle:
        def snapshot(self):
            return {"active_slots": 5, "pending": 1, "slots": 8,
                    "closed": False, "paged": True,
                    "pages_total": 64, "pages_free": 60,
                    "pages_in_use": 4}

    class WarmCacheIdle:
        # no streams; 32 pages pinned ONLY by the prefix store —
        # reclaimable cache must read as idle, not load
        def snapshot(self):
            return {"active_slots": 0, "pending": 0, "slots": 8,
                    "closed": False, "paged": True,
                    "pages_total": 64, "pages_free": 32,
                    "pages_in_use": 32, "pages_evictable": 32}

    agg = MetricsAggregator(clock=lambda: 0.0)
    agg.observe_engine("long", LongContext(), now=1.0)
    w = agg.window("long", 10.0, now=1.0)
    assert w.concurrency == pytest.approx(0.75 * 8)  # pages dominate
    agg.observe_engine("idle", PageIdle(), now=1.0)
    w = agg.window("idle", 10.0, now=1.0)
    assert w.concurrency == pytest.approx(5.0)       # slots dominate
    assert w.queue_depth == pytest.approx(1.0)
    agg.observe_engine("warm", WarmCacheIdle(), now=1.0)
    w = agg.window("warm", 10.0, now=1.0)
    assert w.concurrency == pytest.approx(0.0)       # cache != load


# -- recommender ------------------------------------------------------------


def test_recommender_stable_tracks_target():
    p = AutoscalePolicy(target_concurrency=4.0, min_replicas=1,
                        pow2_packing=False)
    r = Recommender(p, "m")
    d = r.recommend(_stats(12.0), _stats(12.0), current=3, now=0.0)
    assert d.desired == 3 and not d.panic
    # small growth, no panic: 16/4 = 4 replicas (< 2x current 3)
    d = r.recommend(_stats(16.0), _stats(16.0), current=3, now=1.0)
    assert d.desired == 4 and not d.panic


def test_recommender_panic_entry_and_exit():
    p = AutoscalePolicy(target_concurrency=4.0, stable_window_s=60.0,
                        panic_window_s=6.0, panic_threshold=2.0)
    r = Recommender(p, "m")
    # burst: panic window sees 40 in flight, stable still remembers calm
    d = r.recommend(_stats(4.0), _stats(40.0), current=1, now=0.0)
    assert d.panic and d.desired == 10
    # burst sags mid-panic: desired must NOT drop (panic floor)
    d = r.recommend(_stats(4.0), _stats(8.0), current=10, now=10.0)
    assert d.panic and d.desired == 10
    # panic exits only after a full stable window of quiet
    d = r.recommend(_stats(4.0), _stats(4.0), current=10, now=30.0)
    assert d.panic, "still inside the quiet window"
    d = r.recommend(_stats(4.0), _stats(4.0), current=10, now=61.0)
    assert not d.panic
    # post-panic scale-down passes through hysteresis, not a cliff
    assert d.desired == 10  # held by scale_down_delay_s
    d = r.recommend(_stats(4.0), _stats(4.0), current=10, now=95.0)
    assert d.desired == 5  # max_scale_down_rate=2 bounds the step


def test_recommender_scale_up_rate_limit():
    p = AutoscalePolicy(target_concurrency=1.0, max_scale_up_rate=3.0,
                        max_replicas=100)
    r = Recommender(p, "m")
    d = r.recommend(_stats(50.0), _stats(50.0), current=2, now=0.0)
    assert d.desired == 6  # 2 * max_scale_up_rate


def test_recommender_scale_to_zero_needs_grace():
    p = AutoscalePolicy(target_concurrency=4.0, scale_to_zero_grace_s=30.0,
                        scale_down_delay_s=5.0)
    r = Recommender(p, "m")
    idle = _stats(0.0)
    d = r.recommend(idle, idle, current=2, now=0.0)
    assert d.desired >= 1, "grace pending: the last replica stays"
    d = r.recommend(idle, idle, current=1, now=10.0)
    assert d.desired == 1
    d = r.recommend(idle, idle, current=1, now=31.0)
    assert d.desired == 0, "grace elapsed: scale to zero"
    # min_replicas > 0 never goes to zero
    r2 = Recommender(AutoscalePolicy(min_replicas=1), "m")
    d = r2.recommend(idle, idle, current=1, now=0.0)
    d = r2.recommend(idle, idle, current=1, now=1000.0)
    assert d.desired == 1


# -- planner ----------------------------------------------------------------


def test_planner_grants_concrete_free_slices():
    p = AutoscalePolicy(slice_shape="v5e-4", pow2_packing=False)
    plan = CapacityPlanner(p).plan(2, [], _inventory(4))
    assert plan.granted == 2 and len(plan.grow) == 2
    assert not plan.capped and plan.shrink == []
    assert all(s.startswith("v5e-4_") for s in plan.grow)


def test_planner_pow2_packing_rounds_up_when_room():
    p = AutoscalePolicy(slice_shape="v5e-4", pow2_packing=True,
                        max_replicas=16)
    plan = CapacityPlanner(p).plan(3, [], _inventory(8))
    assert plan.granted == 4  # 3 → 4
    assert any("pow2" in e for e in plan.events)
    # no room for 4: falls back to the raw ask of 3
    plan = CapacityPlanner(p).plan(3, [], _inventory(3))
    assert plan.granted == 3 and not plan.capped


def test_planner_degrades_when_inventory_exhausted():
    p = AutoscalePolicy(slice_shape="v5e-4", pow2_packing=False)
    inv = _inventory(6, busy=4)  # only 2 fully-free slices
    plan = CapacityPlanner(p).plan(5, [], inv)
    assert plan.granted == 2 and plan.capped
    assert any("exhausted" in e for e in plan.events)
    # nothing free at all: granted stays at current, still no throw
    plan = CapacityPlanner(p).plan(5, ["v5e-4_9"], _inventory(2, busy=2))
    assert plan.granted == 1 and plan.capped


def test_planner_ignores_partially_busy_and_assigned_slices():
    p = AutoscalePolicy(slice_shape="v5e-8", pow2_packing=False)
    inv = [SliceInfo("v5e-8_0", "v5e-8", 2, 2),
           SliceInfo("v5e-8_1", "v5e-8", 2, 1),   # partially busy
           SliceInfo("v5e-8_2", "v5e-8", 2, 2)]
    plan = CapacityPlanner(p).plan(3, ["v5e-8_0"], inv)
    assert plan.grow == ["v5e-8_2"]
    assert plan.capped


def test_planner_never_grants_draining_slices():
    """A draining replica still owns its slice: even when the inventory
    scan (racing the teardown) reports it free, the planner must not
    double-book it."""
    p = AutoscalePolicy(slice_shape="v5e-4", pow2_packing=False)
    plan = CapacityPlanner(p).plan(2, ["v5e-4_0"], _inventory(3),
                                   busy=["v5e-4_1"])
    assert "v5e-4_1" not in plan.grow
    assert plan.grow == ["v5e-4_2"]


def test_reconciler_excludes_draining_from_regrant():
    p = AutoscalePolicy(target_concurrency=1.0, pow2_packing=False,
                        scale_down_delay_s=1.0, scale_to_zero_grace_s=2.0)
    driver = StubDriver()
    driver.instant_warm = True
    asc = Autoscaler(p, driver, inventory=lambda: _inventory(2),
                     clock=lambda: 0.0)
    asc.watch("m")
    asc.aggregator.request_start("m", now=0.0)
    asc.reconcile("m", now=0.0)          # replica on slice 0
    asc.aggregator.request_finish("m", now=1.0)
    driver.inflight[1] = 1               # straggler: drain will linger
    asc.reconcile("m", now=200.0)
    asc.reconcile("m", now=203.0)        # grace elapsed → draining
    assert asc.status()["models"]["m"]["replicas"]["draining"] == 1
    # demand returns while slice 0 is still draining
    asc.aggregator.request_start("m", now=204.0)
    asc.reconcile("m", now=205.0)
    slices = asc.status()["models"]["m"]["slices"]
    fresh = [s["slice"] for s in slices if s["phase"] != "draining"]
    draining = [s["slice"] for s in slices if s["phase"] == "draining"]
    assert draining == ["v5e-4_0"]
    assert fresh == ["v5e-4_1"], "must not re-grant the draining slice"


def test_planner_shrinks_newest_first():
    p = AutoscalePolicy(slice_shape="v5e-4")
    plan = CapacityPlanner(p).plan(
        1, ["v5e-4_0", "v5e-4_1", "v5e-4_2"], _inventory(4, busy=3))
    assert plan.granted == 1
    assert plan.shrink == ["v5e-4_1", "v5e-4_2"]


# -- reconciler -------------------------------------------------------------


def _autoscaler(policy, driver, free_slices=8, clock=None):
    inv = {"n": free_slices}
    return Autoscaler(
        policy, driver,
        inventory=lambda: _inventory(inv["n"]),
        clock=clock if clock is not None else (lambda: 0.0)), inv


def test_reconciler_warm_before_admit():
    p = AutoscalePolicy(target_concurrency=1.0, pow2_packing=False,
                        min_replicas=0)
    driver = StubDriver()
    asc, _ = _autoscaler(p, driver)
    asc.watch("m")
    assert asc.can_admit("unwatched-model"), "never block static models"
    assert not asc.can_admit("m")
    asc.aggregator.request_start("m", now=0.0)
    asc.reconcile("m", now=1.0)
    # replica created + warmup started, but NOT admitting yet
    assert driver.log[:2] == ["create:v5e-4_0", "warmup:1"]
    assert not asc.can_admit("m")
    asc.reconcile("m", now=2.0)
    assert not asc.can_admit("m"), "still cold after another tick"
    driver.finish_warmup(1)
    asc.reconcile("m", now=3.0)
    assert asc.can_admit("m")


def test_reconciler_drain_before_destroy():
    p = AutoscalePolicy(target_concurrency=1.0, pow2_packing=False,
                        scale_down_delay_s=5.0, scale_to_zero_grace_s=10.0)
    driver = StubDriver()
    driver.instant_warm = True
    clock = {"t": 0.0}
    asc, _ = _autoscaler(p, driver, clock=lambda: clock["t"])
    asc.watch("m")
    asc.aggregator.request_start("m", now=0.0)
    asc.reconcile("m", now=0.0)
    asc.reconcile("m", now=1.0)
    assert asc.can_admit("m")
    # request completes; replica still serving one straggler
    asc.aggregator.request_finish("m", now=2.0)
    driver.inflight[1] = 1
    # idle long enough for grace (windows only remember the horizon)
    t = 130.0
    asc.reconcile("m", now=t)
    asc.reconcile("m", now=t + 11.0)
    assert "drain:1" in driver.log
    assert "destroy:1" not in driver.log, "straggler still in flight"
    assert not asc.can_admit("m"), "draining replica admits nothing"
    driver.inflight[1] = 0
    asc.reconcile("m", now=t + 12.0)
    assert "destroy:1" in driver.log
    st = asc.status()["models"]["m"]
    assert st["replicas"] == {"ready": 0, "warming": 0, "draining": 0}


def test_reconciler_persists_scale_to_registry(tmp_path):
    from kubeflow_tpu.serving.registry import ModelRegistry

    reg = ModelRegistry(str(tmp_path))
    p = AutoscalePolicy(target_concurrency=1.0, pow2_packing=False)
    driver = StubDriver()
    driver.instant_warm = True
    asc = Autoscaler(p, driver, inventory=lambda: _inventory(4),
                     registry=reg, clock=lambda: 0.0)
    asc.watch("m")
    asc.aggregator.request_start("m", now=0.0)
    asc.reconcile("m", now=1.0)
    assert reg.scale("m")["replicas"] == 1
    # the registry REST surface serves the same document
    from kubeflow_tpu.serving.registry import RegistryService

    svc = RegistryService(reg)
    code, body = svc.handle("GET", "/api/registry/models/m/scale", None)
    assert code == 200 and body["replicas"] == 1


def test_registry_scale_roundtrip(tmp_path):
    from kubeflow_tpu.serving.registry import ModelRegistry, RegistryService

    svc = RegistryService(ModelRegistry(str(tmp_path)))
    code, body = svc.handle("POST", "/api/registry/models/m/scale",
                            {"replicas": 3, "reason": "manual"})
    assert code == 200 and body["replicas"] == 3
    code, body = svc.handle("GET", "/api/registry/models/m/scale", None)
    assert code == 200
    assert body["replicas"] == 3 and body["reason"] == "manual"
    code, _ = svc.handle("POST", "/api/registry/models/m/scale",
                         {"replicas": -1})
    assert code == 400
    code, _ = svc.handle("GET", "/api/registry/models/nope/scale", None)
    assert code == 404


# -- proxy + dashboard wiring ----------------------------------------------


def test_proxy_reports_and_holds():
    import io

    from kubeflow_tpu.serving.proxy import PredictProxy

    agg = MetricsAggregator(clock=lambda: 0.0)
    p = AutoscalePolicy(target_concurrency=1.0, pow2_packing=False)
    driver = StubDriver()
    asc = Autoscaler(p, driver, aggregator=agg,
                     inventory=lambda: _inventory(2), clock=lambda: 0.0)
    asc.watch("m")
    proxy = PredictProxy("http://127.0.0.1:1", log_stream=io.StringIO(),
                         reporter=agg, admit_gate=asc)
    code, body = proxy.handle("POST", "/model/m:predict",
                              {"instances": [1]})
    assert code == 503 and "no ready replica" in body["error"]
    # the held request still counted: its telemetry wakes the loop
    assert agg.window("m", 10.0, now=1.0).rps > 0
    assert agg.inflight("m") == 0, "finish reported after the 503"
    # unwatched model: gate passes, forward fails (no backend) → 502
    code, _ = proxy.handle("POST", "/model/other:predict", {})
    assert code == 502


def test_dashboard_autoscale_view():
    from kubeflow_tpu.dashboard.server import DashboardApi
    from kubeflow_tpu.k8s.client import FakeKubeClient

    p = AutoscalePolicy(target_concurrency=1.0, pow2_packing=False)
    driver = StubDriver()
    driver.instant_warm = True
    asc = Autoscaler(p, driver, inventory=lambda: _inventory(2),
                     clock=lambda: 0.0)
    asc.watch("m")
    asc.aggregator.request_start("m", now=0.0)
    asc.reconcile("m", now=1.0)
    api = DashboardApi(FakeKubeClient(), autoscaler=asc,
                       authorize=lambda *a: True)
    code, body = api.handle("GET", "/api/metrics/autoscale", None)
    assert code == 200
    assert body["models"]["m"]["replicas"]["ready"] == 1
    assert body["policy"]["target_concurrency"] == 1.0


def test_autoscale_service_routes():
    from kubeflow_tpu.autoscale.service import AutoscaleService

    p = AutoscalePolicy(target_concurrency=1.0, pow2_packing=False)
    asc = Autoscaler(p, StubDriver(), inventory=lambda: _inventory(2),
                     clock=lambda: 0.0)
    svc = AutoscaleService(asc)
    assert svc.handle("GET", "/healthz", None)[0] == 200
    code, _ = svc.handle("POST", "/api/autoscale/watch", {"model": "m"})
    assert code == 200
    code, _ = svc.handle("POST", "/api/autoscale/report",
                         {"model": "m", "event": "start"})
    assert code == 200
    assert asc.aggregator.inflight("m") == 1
    code, _ = svc.handle("POST", "/api/autoscale/report",
                         {"model": "m", "event": "observe",
                          "queueDepth": 2, "activeSlots": 4})
    assert code == 200
    code, body = svc.handle("GET", "/api/autoscale/status", None)
    assert code == 200 and "m" in body["models"]
    assert svc.handle("POST", "/api/autoscale/report",
                      {"model": "m", "event": "bogus"})[0] == 400
    # the remote activator gate endpoint
    code, body = svc.handle("GET", "/api/autoscale/can_admit?model=m",
                            None)
    assert code == 200 and body["canAdmit"] is False  # zero replicas
    code, body = svc.handle(
        "GET", "/api/autoscale/can_admit?model=unwatched", None)
    assert code == 200 and body["canAdmit"] is True
    assert svc.handle("GET", "/api/autoscale/can_admit", None)[0] == 400


def test_remote_admit_gate_fails_open():
    """A dead autoscaler must degrade to static serving, not a 503
    wall — the gate admits when its status GET can't be answered, and
    the fail-open is COUNTED (``kftpu_proxy_admit_gate_degraded_
    total``), never a silent pass: traffic flows, on-call learns the
    activator is blind."""
    from kubeflow_tpu.serving.proxy import RemoteAdmitGate
    from kubeflow_tpu.utils import DEFAULT_REGISTRY

    degraded = DEFAULT_REGISTRY.counter(
        "kftpu_proxy_admit_gate_degraded_total")
    before = degraded.get()
    gate = RemoteAdmitGate("http://127.0.0.1:1", timeout_s=0.2)
    assert gate.can_admit("m") is True
    assert degraded.get() == before + 1
    # and the verdict is cached (no second blocking call inside the TTL)
    assert gate._cache["m"][1] is True
    assert gate.can_admit("m") is True
    assert degraded.get() == before + 1  # cache hit: no second probe


def test_engine_snapshot_shape():
    """The aggregator's engine poll contract, without building a real
    engine: snapshot() exists on DecodeEngine and returns these keys."""
    import inspect

    from kubeflow_tpu.serving.engine import DecodeEngine

    src = inspect.getsource(DecodeEngine.snapshot)
    for key in ("active_slots", "pending", "slots", "closed",
                "pages_total", "pages_free"):
        assert key in src


# -- the simulated load test (acceptance gate) ------------------------------


def test_simulated_burst_drain_and_rearrival():
    """End-to-end on stubs + fake clock:

    1. steady trickle keeps one replica;
    2. a burst pushes the panic window past threshold → panic scale-up
       lands within ONE panic window of the burst;
    3. inventory caps the panic ask → partial grant + event;
    4. idle drains everything to zero;
    5. a re-arriving request is held (503-style gate) until the warmed
       replica flips ready, then admits.
    """
    policy = AutoscalePolicy(
        target_concurrency=4.0,
        stable_window_s=60.0,
        panic_window_s=6.0,
        panic_threshold=2.0,
        max_scale_up_rate=100.0,
        scale_down_delay_s=10.0,
        scale_to_zero_grace_s=20.0,
        slice_shape="v5e-4",
        pow2_packing=False,
        max_replicas=16,
    )
    driver = StubDriver()
    inv = {"n": 4}
    asc = Autoscaler(policy, driver,
                     inventory=lambda: _inventory(inv["n"]),
                     clock=lambda: 0.0)
    agg = asc.aggregator
    asc.watch("m")

    # -- phase 1: trickle → one replica, warmed, admitting ------------------
    agg.request_start("m", now=0.0)
    asc.reconcile("m", now=1.0)
    assert len(driver.warm) == 1
    driver.finish_warmup(1)
    asc.reconcile("m", now=2.0)
    assert asc.can_admit("m")
    agg.request_finish("m", now=3.0)

    # -- phase 2: burst of 40 concurrent requests at t=10 -------------------
    for i in range(40):
        agg.request_start("m", now=10.0 + i * 0.01)
    # one reconcile tick INSIDE the panic window after the burst:
    d = asc.reconcile("m", now=11.0)
    assert d.panic, "burst must flip panic within one panic window"
    assert d.desired > 4, "window demand must exceed the inventory"
    status = asc.status()["models"]["m"]
    total = (status["replicas"]["ready"] + status["replicas"]["warming"])
    # window-averaged demand is ~21 concurrency → 6 replicas, but only
    # 4 slices exist → partial grant + degradation event, no throw
    assert total == 4, f"want all 4 slices granted, got {total}"
    assert status["capped"]
    assert any("exhausted" in e["message"] for e in status["events"])

    # warm the burst replicas; they admit
    for h in list(driver.warm):
        driver.finish_warmup(h)
    asc.reconcile("m", now=12.0)
    assert asc.status()["models"]["m"]["replicas"]["ready"] == 4

    # -- phase 3: burst ends; idle → drain + scale-to-zero ------------------
    for _ in range(40):
        agg.request_finish("m", now=20.0)
    # windows roll past the horizon, grace elapses, hysteresis expires
    t0 = 200.0
    asc.reconcile("m", now=t0)          # idle timer starts
    asc.reconcile("m", now=t0 + 21.0)   # grace elapsed → drain all
    st = asc.status()["models"]["m"]
    assert st["replicas"]["draining"] == 4 and st["replicas"]["ready"] == 0
    assert not asc.can_admit("m")
    asc.reconcile("m", now=t0 + 22.0)   # in_flight 0 → destroyed
    st = asc.status()["models"]["m"]
    assert st["replicas"] == {"ready": 0, "warming": 0, "draining": 0}
    assert st["desired"] == 0
    destroys = [x for x in driver.log if x.startswith("destroy:")]
    assert len(destroys) == 4

    # -- phase 4: re-arrival against zero replicas --------------------------
    t1 = t0 + 400.0  # far past the horizon: windows are clean
    agg.request_start("m", now=t1)
    asc.reconcile("m", now=t1 + 1.0)
    st = asc.status()["models"]["m"]
    assert st["replicas"]["warming"] == 1
    assert not asc.can_admit("m"), \
        "request must be HELD until the replica warms"
    asc.reconcile("m", now=t1 + 2.0)
    assert not asc.can_admit("m"), "still cold, still held"
    new_handle = max(driver.warm)
    driver.finish_warmup(new_handle)
    asc.reconcile("m", now=t1 + 3.0)
    assert asc.can_admit("m"), "warmed replica admits the held request"
    # warmup strictly precedes admission in the driver's event order
    warm_idx = driver.log.index(f"warmup:{new_handle}")
    assert all(not e.startswith("destroy") for e in
               driver.log[warm_idx:]), "no churn during the re-arrival"
