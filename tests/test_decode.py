"""KV-cache decoding tests: cached generation must reproduce the full
forward pass exactly (the cache is an optimization, never a semantics
change), padded prompts must not leak into attention, and the whole
loop must be jit-compilable with static shapes.

No reference counterpart: the reference serves opaque TF-Serving
containers and has no generation path — this is TPU-native capability
(SURVEY §7 design stance: the framework owns the model math).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubeflow_tpu.models import Transformer, TransformerConfig
from kubeflow_tpu.models.decode import (
    decode_step,
    generate,
    make_generate,
    prefill,
)
from kubeflow_tpu.serving import transformer_export_config


def small_config(**kw):
    base = dict(vocab_size=97, d_model=32, n_layers=2, n_heads=4,
                n_kv_heads=2, d_ff=64, max_seq_len=32,
                dtype=jnp.float32, remat=False)
    base.update(kw)
    return TransformerConfig(**base)


@pytest.fixture(scope="module")
def setup():
    config = small_config()
    model = Transformer(config)
    prompt = jax.random.randint(jax.random.key(1), (2, 5), 0,
                                config.vocab_size)
    params = model.init(jax.random.key(0), prompt)["params"]
    return config, model, params, prompt


def full_forward_greedy(model, params, prompt, n):
    """Oracle: re-run the full (non-cached) forward each step."""
    tokens = prompt
    out = []
    for _ in range(n):
        logits = model.apply({"params": params}, tokens)
        nxt = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
        out.append(nxt)
        tokens = jnp.concatenate([tokens, nxt[:, None]], axis=1)
    return jnp.stack(out, axis=1)


@pytest.mark.slow  # multi-second XLA compiles; tier-1 runs the fast twin paths
def test_greedy_matches_full_forward(setup):
    config, model, params, prompt = setup
    want = full_forward_greedy(model, params, prompt, 6)
    got = generate(config, params, prompt, max_new_tokens=6)
    np.testing.assert_array_equal(got, want)


def test_prefill_logits_match_forward(setup):
    config, model, params, prompt = setup
    full = model.apply({"params": params}, prompt)
    last, _ = prefill(config, params, prompt)
    np.testing.assert_allclose(last, full[:, -1], atol=1e-5)


def test_padded_prompt_matches_unpadded(setup):
    """Right-padding to a bucket + true_len must change nothing: the
    padded tail is masked until overwritten."""
    config, model, params, prompt = setup
    pad = jnp.zeros((prompt.shape[0], 11 - prompt.shape[1]), jnp.int32)
    padded = jnp.concatenate([prompt, pad], axis=1)
    want = generate(config, params, prompt, max_new_tokens=5)
    got = generate(config, params, padded, max_new_tokens=5,
                   true_len=prompt.shape[1])
    np.testing.assert_array_equal(got, want)


@pytest.mark.slow  # multi-second XLA compiles; tier-1 runs the fast twin paths
def test_ragged_batch_matches_per_row_oracle(setup):
    """Per-row true lengths: each row of a ragged batch must generate
    exactly what it would generate alone (physical slot == logical
    position per row, so causality is exact)."""
    config, model, params, _ = setup
    rng = jax.random.key(9)
    lens = [3, 5, 7]
    rows = [jax.random.randint(jax.random.fold_in(rng, i), (1, n), 0,
                               config.vocab_size)
            for i, n in enumerate(lens)]
    width = max(lens)
    padded = jnp.zeros((len(rows), width), jnp.int32)
    for i, r in enumerate(rows):
        padded = padded.at[i, :lens[i]].set(r[0])

    got = generate(config, params, padded, max_new_tokens=5,
                   true_len=jnp.asarray(lens, jnp.int32))
    for i, r in enumerate(rows):
        want = full_forward_greedy(model, params, r, 5)
        np.testing.assert_array_equal(got[i:i + 1], want,
                                      err_msg=f"row {i} (len {lens[i]})")


def test_decode_step_advances_one_token(setup):
    config, model, params, prompt = setup
    last, cache = prefill(config, params, prompt)
    tok = jnp.argmax(last, axis=-1).astype(jnp.int32)
    logits, cache = decode_step(config, params, cache, tok)
    # oracle: full forward over prompt+tok
    full = model.apply({"params": params},
                       jnp.concatenate([prompt, tok[:, None]], axis=1))
    np.testing.assert_allclose(logits, full[:, -1], atol=1e-5)


def test_generate_is_jittable(setup):
    config, model, params, prompt = setup
    fn = make_generate(config, max_new_tokens=4)
    got = fn(params, prompt, jnp.int32(prompt.shape[1]), jax.random.key(0))
    want = generate(config, params, prompt, max_new_tokens=4)
    np.testing.assert_array_equal(got, want)
    # second call with same shapes hits the jit cache (no retrace error)
    fn(params, prompt, jnp.int32(prompt.shape[1]), jax.random.key(1))


def test_sampling_is_reproducible_and_varies(setup):
    config, model, params, prompt = setup
    a = generate(config, params, prompt, max_new_tokens=8,
                 temperature=1.0, rng=jax.random.key(7))
    b = generate(config, params, prompt, max_new_tokens=8,
                 temperature=1.0, rng=jax.random.key(7))
    c = generate(config, params, prompt, max_new_tokens=8,
                 temperature=1.0, rng=jax.random.key(8))
    np.testing.assert_array_equal(a, b)
    assert not np.array_equal(a, c)  # overwhelmingly likely to differ


def test_sampling_requires_rng(setup):
    config, _, params, prompt = setup
    with pytest.raises(ValueError, match="rng"):
        generate(config, params, prompt, max_new_tokens=2, temperature=0.7)


def test_unscanned_layers_decode(setup):
    """scan_layers=False keeps per-block caches; same numerics."""
    config = small_config(scan_layers=False)
    model = Transformer(config)
    prompt = jax.random.randint(jax.random.key(1), (1, 4), 0,
                                config.vocab_size)
    params = model.init(jax.random.key(0), prompt)["params"]
    want = full_forward_greedy(model, params, prompt, 4)
    got = generate(config, params, prompt, max_new_tokens=4)
    np.testing.assert_array_equal(got, want)


def test_moe_decode(setup):
    config = small_config(n_experts=4, experts_per_token=2)
    model = Transformer(config)
    prompt = jax.random.randint(jax.random.key(1), (2, 4), 0,
                                config.vocab_size)
    params = model.init(jax.random.key(0), prompt)["params"]
    want = full_forward_greedy(model, params, prompt, 3)
    got = generate(config, params, prompt, max_new_tokens=3)
    np.testing.assert_array_equal(got, want)


def test_serving_generate_endpoint(tmp_path, setup):
    """:generate over live HTTP — export a transformer, generate through
    the model server, and match the in-process greedy oracle."""
    import json
    import urllib.request

    from kubeflow_tpu.serving import ModelServer, export_model

    config, model, params, prompt = setup
    export_model(str(tmp_path / "lm"), "transformer", params, version=1,
                 config=transformer_export_config(config))
    srv = ModelServer(str(tmp_path), port=0, poll_interval_s=3600)
    port = srv.start()
    try:
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/v1/models/lm:generate",
            data=json.dumps({
                "prompt_tokens": np.asarray(prompt).tolist(),
                "max_new_tokens": 4}).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=120) as resp:
            out = json.load(resp)
        want = full_forward_greedy(model, params, prompt, 4)
        np.testing.assert_array_equal(np.asarray(out["tokens"]), want)
        assert out["tokens_per_sec"] > 0

        # non-LM kinds refuse :generate with a clear 400
        import jax as _jax
        from kubeflow_tpu.models import MnistCnn

        m = MnistCnn()
        export_model(str(tmp_path / "mnist"), "mnist",
                     m.init(_jax.random.key(0),
                            jnp.zeros((1, 28, 28, 1)))["params"], version=1)
        srv.repo.refresh()
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/v1/models/mnist:generate",
            data=json.dumps({"prompt_tokens": [[1]]}).encode(),
            headers={"Content-Type": "application/json"})
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(req, timeout=30)
        assert ei.value.code == 400
    finally:
        srv.stop()


def test_grpc_generate_matches_rest(tmp_path, setup):
    """The gRPC Generate RPC (binary prompt tensors) and the REST
    :generate endpoint share one core — same tokens out."""
    from kubeflow_tpu.serving import ModelServer, export_model
    from kubeflow_tpu.serving.grpc_server import PredictClient, serve_grpc

    config, model, params, prompt = setup
    export_model(str(tmp_path / "lm"), "transformer", params, version=1,
                 config=transformer_export_config(config))
    srv = ModelServer(str(tmp_path), port=0, poll_interval_s=3600)
    srv.start()
    grpc_srv, grpc_port = serve_grpc(srv.repo, 0)
    client = PredictClient(f"127.0.0.1:{grpc_port}")
    try:
        tokens, version = client.generate(
            "lm", np.asarray(prompt), max_new_tokens=4)
        want = full_forward_greedy(model, params, prompt, 4)
        np.testing.assert_array_equal(tokens, want)
        assert version == 1
        # right-padded prompt with an out-of-vocab PAD id: the pad
        # columns never reach the model, so this must succeed
        padded = np.full((prompt.shape[0], 8), -1, np.int32)
        padded[:, :prompt.shape[1]] = prompt
        tokens_p, _ = client.generate("lm", padded, max_new_tokens=4,
                                      true_len=prompt.shape[1])
        np.testing.assert_array_equal(tokens_p, want)
        # true_len whose pow2 bucket is below the padded width must
        # still serve (regression: bucket sized from true_len used to
        # crash the broadcast into the narrower bucket)
        wide = np.zeros((prompt.shape[0], 16), np.int32)
        wide[:, :prompt.shape[1]] = prompt
        tokens_w, _ = client.generate("lm", wide, max_new_tokens=4,
                                      true_len=prompt.shape[1])
        np.testing.assert_array_equal(tokens_w, want)

        # errors surface as INVALID_ARGUMENT with the core's message
        import grpc as _grpc

        with pytest.raises(_grpc.RpcError) as ei:
            client.generate("lm", np.asarray(prompt), max_new_tokens=999)
        assert ei.value.code() == _grpc.StatusCode.INVALID_ARGUMENT
        assert "context" in ei.value.details()
        # a scalar prompt tensor is a clean INVALID_ARGUMENT, not UNKNOWN
        with pytest.raises(_grpc.RpcError) as ei:
            client.generate("lm", np.int32(5))
        assert ei.value.code() == _grpc.StatusCode.INVALID_ARGUMENT
    finally:
        client.close()
        grpc_srv.stop(grace=None)
        srv.stop()


def test_serving_generate_validation(tmp_path, setup):
    from kubeflow_tpu.serving import export_model
    from kubeflow_tpu.serving.server import ModelServer

    config, model, params, _ = setup
    export_model(str(tmp_path / "lm"), "transformer", params, version=1,
                 config=transformer_export_config(config))
    srv = ModelServer(str(tmp_path), port=0, poll_interval_s=3600)
    srv.start()
    try:
        # ragged REST batches are first-class now: each row generates
        # from its own length
        code, out = srv.handle_generate("lm", None,
                                        {"prompt_tokens": [[1, 2], [3]],
                                         "max_new_tokens": 2})
        assert code == 200, out
        assert len(out["tokens"]) == 2
        code, out = srv.handle_generate("lm", None, {})
        assert code == 400
        # context overflow must be a 400, not silently-clamped garbage
        code, out = srv.handle_generate(
            "lm", None, {"prompt_tokens": [[1] * 8],
                         "max_new_tokens": 1000})
        assert code == 400 and "context" in out["error"]
        # negative temperature inverts the distribution — reject
        code, out = srv.handle_generate(
            "lm", None, {"prompt_tokens": [[1, 2]], "temperature": -0.7})
        assert code == 400 and "temperature" in out["error"]
        # oversized batch rejected like the predict path
        code, out = srv.handle_generate(
            "lm", None, {"prompt_tokens": [[1, 2]] * 99})
        assert code == 400 and "batch" in out["error"]
        # a prompt past half the context must still generate: the budget
        # is ctx - true_len, NOT ctx - pow2_bucket (ctx=32 here)
        code, out = srv.handle_generate(
            "lm", None, {"prompt_tokens": [[1] * 20],
                         "max_new_tokens": 4})
        assert code == 200, out
        assert len(out["tokens"][0]) == 4
        # misshaped (3-D) prompts are a 400, not a handler crash
        code, out = srv.handle_generate(
            "lm", None, {"prompt_tokens": [[[1, 2], [3, 4]]]})
        assert code == 400
        assert ("2-D" in out["error"]
                or "bad prompt_tokens" in out["error"])
        # out-of-vocab ids would silently clamp in the embedding
        code, out = srv.handle_generate(
            "lm", None, {"prompt_tokens": [[999999, 1]]})
        assert code == 400 and "token ids" in out["error"]
        code, out = srv.handle_generate(
            "lm", None, {"prompt_tokens": [[-5, 1]]})
        assert code == 400
    finally:
        srv.stop()


@pytest.mark.slow  # multi-second XLA compiles; tier-1 runs the flagship twin
def test_lm_example_generate_small_context(tmp_path, capsys):
    """--generate with a tiny --seq-len must sample (or skip cleanly),
    never crash in the scan."""
    from kubeflow_tpu.examples.lm import main

    main(["--steps", "2", "--per-device-batch", "1", "--seq-len", "8",
          "--vocab-size", "32", "--d-model", "8", "--n-layers", "1",
          "--n-heads", "2", "--d-ff", "16", "--log-every", "2",
          "--generate", "4"])
    out = capsys.readouterr().out
    assert "sample_tokens" in out


def test_serving_generate_near_context_end_buckets_pow2(tmp_path, setup):
    """A prompt near the context end must not mint per-length compiled
    programs: the clamped new-token bucket stays a power of two."""
    from kubeflow_tpu.serving import export_model
    from kubeflow_tpu.serving.server import ModelServer

    config, _, params, _ = setup  # max_seq_len = 32
    export_model(str(tmp_path / "lm"), "transformer", params, version=1,
                 config=transformer_export_config(config))
    srv = ModelServer(str(tmp_path), port=0, poll_interval_s=3600)
    srv.start()
    try:
        lm = srv.repo.get("lm")
        # budgets 7, 6, 5 all round down to the pow2 bucket 4
        for tl in (25, 26, 27):
            code, _ = srv.handle_generate(
                "lm", None, {"prompt_tokens": [[1] * tl],
                             "max_new_tokens": 3})
            assert code == 200
        assert lm.generate._cache_size() == 1
        # exact-fit tail: prompt 29 + max_new 3 = 32 fits even though
        # pow2(3)=4 does not — served exactly, not rejected
        code, out = srv.handle_generate(
            "lm", None, {"prompt_tokens": [[1] * 29],
                         "max_new_tokens": 3})
        assert code == 200, out
        assert len(out["tokens"][0]) == 3
        # but an unservable ask is an honest 400
        code, out = srv.handle_generate(
            "lm", None, {"prompt_tokens": [[1] * 30],
                         "max_new_tokens": 3})
        assert code == 400 and "context" in out["error"]
    finally:
        srv.stop()


def test_serving_ragged_rows_match_solo_requests(tmp_path, setup):
    """Each row of a ragged REST batch must generate exactly what a
    solo request for that prompt generates."""
    from kubeflow_tpu.serving import ModelServer, export_model

    config, model, params, _ = setup
    export_model(str(tmp_path / "lm"), "transformer", params, version=1,
                 config=transformer_export_config(config))
    srv = ModelServer(str(tmp_path), port=0, poll_interval_s=3600)
    srv.start()
    try:
        rows = [[5, 9, 2], [7, 1, 3, 8, 4]]
        code, batch = srv.handle_generate(
            "lm", None, {"prompt_tokens": rows, "max_new_tokens": 4})
        assert code == 200, batch
        for i, row in enumerate(rows):
            code, solo = srv.handle_generate(
                "lm", None, {"prompt_tokens": [row],
                             "max_new_tokens": 4})
            assert code == 200
            assert batch["tokens"][i] == solo["tokens"][0], f"row {i}"
        # a REST client that pads client-side and passes true_len gets
        # the unpadded behavior (the old documented contract)
        code, via_tl = srv.handle_generate(
            "lm", None, {"prompt_tokens": [rows[0] + [0, 0]],
                         "true_len": 3, "max_new_tokens": 4})
        assert code == 200, via_tl
        code, solo = srv.handle_generate(
            "lm", None, {"prompt_tokens": [rows[0]],
                         "max_new_tokens": 4})
        assert via_tl["tokens"][0] == solo["tokens"][0]
    finally:
        srv.stop()


def test_generate_rejects_context_overrun(setup):
    """The library API errors on overruns instead of silently clamping
    cache writes (max_seq_len=32 in the fixture)."""
    config, _, params, prompt = setup
    with pytest.raises(ValueError, match="max_seq_len"):
        generate(config, params, prompt,
                 max_new_tokens=config.max_seq_len)


def test_serving_generate_temperatures_share_one_compile(tmp_path, setup):
    """Distinct temperatures must reuse one compiled sampling program —
    temperature is traced, only greedy-vs-sampling is static."""
    import jax as _jax

    from kubeflow_tpu.serving import export_model
    from kubeflow_tpu.serving.server import ModelServer

    config, model, params, prompt = setup
    export_model(str(tmp_path / "lm"), "transformer", params, version=1,
                 config=transformer_export_config(config))
    srv = ModelServer(str(tmp_path), port=0, poll_interval_s=3600)
    srv.start()
    try:
        lm = srv.repo.get("lm")
        body = {"prompt_tokens": np.asarray(prompt).tolist(),
                "max_new_tokens": 2, "seed": 1}
        for t in (0.5, 0.7, 0.9):
            code, _ = srv.handle_generate("lm", None,
                                          {**body, "temperature": t})
            assert code == 200
        # one sampling cache entry despite three temperatures
        assert lm.generate._cache_size() == 1
    finally:
        srv.stop()


def test_decode_on_sharded_mesh(setup):
    """Generation with tensor-parallel-sharded params on the virtual
    mesh: the multi-chip serving path. Results must match unsharded
    greedy decode exactly."""
    from jax.sharding import NamedSharding

    from conftest import shard_params
    from kubeflow_tpu.parallel import MeshConfig, create_mesh
    from kubeflow_tpu.parallel.mesh import (
        logical_to_mesh_axes,
        mesh_context,
    )

    config, model, params, prompt = setup
    want = generate(config, params, prompt, max_new_tokens=5)

    mesh = create_mesh(MeshConfig(dp=2, tp=4))
    sharded = shard_params(params, mesh)
    tokens = jax.device_put(
        prompt, NamedSharding(mesh, logical_to_mesh_axes(("batch", None))))
    with mesh_context(mesh):
        got = jax.jit(lambda p, t: generate(
            config, p, t, max_new_tokens=5))(sharded, tokens)
    np.testing.assert_array_equal(got, want)


@pytest.mark.slow  # multi-second XLA compiles; tier-1 runs the fast twin paths
def test_lm_example_train_generate_export(tmp_path, capsys):
    """The flagship loop end to end: train → greedy sample → export →
    reload with a generate-capable LoadedModel."""
    from kubeflow_tpu.examples.lm import main
    from kubeflow_tpu.serving import load_latest

    loss = main(["--steps", "3", "--per-device-batch", "1",
                 "--seq-len", "16", "--vocab-size", "64",
                 "--d-model", "16", "--n-layers", "1", "--n-heads", "2",
                 "--d-ff", "32", "--log-every", "3",
                 "--export", str(tmp_path / "lm"), "--generate", "4"])
    assert loss == loss  # finite
    out = capsys.readouterr().out
    assert "sample_tokens" in out and "exported" in out
    m = load_latest(str(tmp_path / "lm"))
    assert m.kind == "transformer" and m.generate is not None
    assert m.max_seq_len == 16 and m.vocab_size == 64


def test_softcap_decode():
    config = small_config(logits_softcap=30.0)
    model = Transformer(config)
    prompt = jax.random.randint(jax.random.key(1), (1, 3), 0,
                                config.vocab_size)
    params = model.init(jax.random.key(0), prompt)["params"]
    want = full_forward_greedy(model, params, prompt, 3)
    got = generate(config, params, prompt, max_new_tokens=3)
    np.testing.assert_array_equal(got, want)
