"""Golden manifest tests — the reference's jsonnet-test tier
(``kubeflow/tf-training/tests/tf-job_test.jsonnet``) re-done for the
Python component registry."""

import pytest

from kubeflow_tpu.config import ComponentSpec, DeploymentConfig, preset
from kubeflow_tpu.manifests import (
    get_component,
    list_components,
    merge_params,
    render_all,
    render_component,
)


@pytest.fixture
def config():
    return DeploymentConfig(name="demo", components=[
        ComponentSpec("tpujob-operator"),
        ComponentSpec("serving", params={"name": "resnet", "tpu_chips": 4}),
        ComponentSpec("dashboard"),
    ])


def test_registry_lists_builtins():
    names = [c.name for c in list_components()]
    assert {"tpujob-operator", "serving", "dashboard"} <= set(names)


def test_unknown_component_raises():
    with pytest.raises(KeyError, match="unknown component"):
        get_component("does-not-exist")


def test_unknown_param_raises():
    comp = get_component("serving")
    with pytest.raises(ValueError, match="unknown params"):
        merge_params(comp, {"nonsense": 1})


def test_tpujob_operator_golden(config):
    objs = render_component(config, ComponentSpec("tpujob-operator"))
    kinds = [o["kind"] for o in objs]
    assert kinds == ["CustomResourceDefinition", "ServiceAccount", "ClusterRole",
                     "ClusterRoleBinding", "Deployment", "Service"]
    crd = objs[0]
    assert crd["metadata"]["name"] == "tpujobs.kubeflow-tpu.org"
    cols = crd["spec"]["versions"][0]["additionalPrinterColumns"]
    assert [c["name"] for c in cols] == ["State", "Slices", "Age"]
    svc = objs[-1]
    assert svc["metadata"]["annotations"]["prometheus.io/scrape"] == "true"
    deploy = objs[4]
    env = {e["name"]: e["value"]
           for e in deploy["spec"]["template"]["spec"]["containers"][0]["env"]}
    assert env["KFTPU_GANG_SCHEDULING"] == "true"


def test_tpujob_operator_namespace_scope(config):
    objs = render_component(
        config, ComponentSpec("tpujob-operator", params={"cluster_scope": False})
    )
    deploy = [o for o in objs if o["kind"] == "Deployment"][0]
    env = {e["name"]: e["value"]
           for e in deploy["spec"]["template"]["spec"]["containers"][0]["env"]}
    assert env["KFTPU_OPERATOR_NAMESPACE"] == "kubeflow"


def test_serving_requests_tpu(config):
    objs = render_component(
        config, ComponentSpec("serving", params={"tpu_chips": 4})
    )
    deploy = objs[0]
    res = deploy["spec"]["template"]["spec"]["containers"][0]["resources"]
    assert res["limits"]["google.com/tpu"] == 4
    svc = objs[1]
    ports = {p["name"]: p["port"] for p in svc["spec"]["ports"]}
    assert ports == {"rest": 8500, "grpc": 9000}  # tf-serving parity ports


def test_tensorboard_golden(config):
    objs = render_component(config, ComponentSpec("tensorboard"))
    kinds = [x["kind"] for x in objs]
    # the PVC renders too, so the preset happy path schedules without a
    # separately-created claim
    assert kinds == ["PersistentVolumeClaim", "Deployment", "Service"]
    pvc, deploy, svc = objs
    assert pvc["metadata"]["name"] == "training-logs"
    ctr = deploy["spec"]["template"]["spec"]["containers"][0]
    assert "--logdir=/logs" in ctr["args"]
    assert ctr["volumeMounts"][0]["readOnly"] is True
    vols = deploy["spec"]["template"]["spec"]["volumes"]
    assert vols[0]["persistentVolumeClaim"]["claimName"] == "training-logs"
    assert svc["spec"]["ports"][0]["targetPort"] == 6006


def test_tensorboard_existing_claim_skips_pvc(config):
    objs = render_component(config, ComponentSpec(
        "tensorboard", {"create_pvc": False}))
    assert [x["kind"] for x in objs] == ["Deployment", "Service"]


def test_monitoring_sidecar_from_platform_params():
    """gcp-tpu users fill platform_params once; the Stackdriver sidecar
    must pick the project up from there."""
    from kubeflow_tpu.config.presets import preset

    cfg = preset("gcp-tpu", "demo")
    cfg.platform_params.update(project="my-proj", zone="us-central2-b",
                               cluster="demo-cluster")
    objs = render_component(cfg, ComponentSpec("monitoring"))
    deploy = next(o for o in objs if o["kind"] == "Deployment")
    ctrs = deploy["spec"]["template"]["spec"]["containers"]
    sidecar = next(c for c in ctrs if c["name"] == "stackdriver-sidecar")
    assert "--stackdriver.project-id=my-proj" in sidecar["args"]
    assert any("cluster-name=demo-cluster" in a for a in sidecar["args"])


def test_tensorboard_gcs_and_istio(config):
    objs = render_component(config, ComponentSpec("tensorboard", params={
        "log_dir": "gs://bucket/logs", "pvc": "", "inject_istio": True}))
    kinds = [x["kind"] for x in objs]
    assert kinds == ["Deployment", "Service", "VirtualService"]
    ctr = objs[0]["spec"]["template"]["spec"]["containers"][0]
    assert "--logdir=gs://bucket/logs" in ctr["args"]
    assert "volumeMounts" not in ctr  # gs:// read directly, no PVC
    vs = objs[2]
    match = vs["spec"]["http"][0]["match"][0]["uri"]["prefix"]
    assert match == "/tensorboard/tensorboard/"


def test_standard_preset_includes_tuning_and_workflows():
    cfg = preset("standard", "demo")
    names = [c.name for c in cfg.components]
    assert "tuning" in names and "workflows" in names
    objs = render_all(cfg)
    kinds = {(x["kind"], x["metadata"]["name"]) for x in objs}
    assert ("CustomResourceDefinition", "studies.kubeflow-tpu.org") in kinds \
        or any(k == "CustomResourceDefinition" and "stud" in n
               for k, n in kinds)
    assert any("workflow" in n for k, n in kinds if k == "Deployment")


def test_render_all_prepends_namespace(config):
    objs = render_all(config)
    assert objs[0]["kind"] == "Namespace"
    assert objs[0]["metadata"]["name"] == "kubeflow"
    # every namespaced object lands in the deployment namespace
    for obj in objs[1:]:
        ns = obj["metadata"].get("namespace")
        if obj["kind"] not in ("CustomResourceDefinition", "ClusterRole",
                               "ClusterRoleBinding", "Namespace"):
            assert ns == "kubeflow", obj["kind"]


def test_presets_render():
    for name in ("minimal", "standard", "gcp-tpu"):
        cfg = preset(name, "demo")
        objs = render_all(cfg)
        assert objs, name


def test_config_yaml_roundtrip(config):
    text = config.to_yaml()
    back = DeploymentConfig.from_yaml(text)
    assert back.to_dict() == config.to_dict()
    assert back.component("serving").params["tpu_chips"] == 4


def test_usage_reporting_component(config):
    objs = render_component(config, ComponentSpec("usage-reporting", params={
        "collector_url": "http://collector:8765/report",
        "cluster_id": "fixed-id"}))
    kinds = [x["kind"] for x in objs]
    assert kinds == ["ServiceAccount", "ClusterRole", "ClusterRoleBinding",
                     "Deployment"]
    role = objs[1]
    assert role["rules"][0]["resources"] == ["nodes"]  # read-only, nodes only
    env = {e["name"]: e["value"] for e in
           objs[3]["spec"]["template"]["spec"]["containers"][0]["env"]}
    assert env["KFTPU_USAGE_CLUSTER_ID"] == "fixed-id"
    # opt-out renders nothing
    assert render_component(
        config, ComponentSpec("usage-reporting",
                              params={"enabled": False})) == []


def test_monitoring_component(config):
    import yaml as _yaml

    objs = render_component(config, ComponentSpec("monitoring"))
    kinds = [x["kind"] for x in objs]
    assert kinds == ["ServiceAccount", "ClusterRole", "ClusterRoleBinding",
                     "ConfigMap", "Deployment", "Service"]
    scrape = _yaml.safe_load(objs[3]["data"]["prometheus.yaml"])
    relabels = scrape["scrape_configs"][0]["relabel_configs"]
    assert relabels[0]["action"] == "keep" and relabels[0]["regex"] == "true"
    # the annotated metrics port/path must win over raw endpoint ports
    targets = {r.get("target_label") for r in relabels}
    assert {"__address__", "__metrics_path__"} <= targets
    # no project -> no stackdriver sidecar
    ctrs = objs[4]["spec"]["template"]["spec"]["containers"]
    assert [c["name"] for c in ctrs] == ["prometheus"]

    objs = render_component(config, ComponentSpec("monitoring", params={
        "project": "my-proj", "cluster": "demo", "zone": "us-east5-a"}))
    deploy = [x for x in objs if x["kind"] == "Deployment"][0]
    ctrs = deploy["spec"]["template"]["spec"]["containers"]
    assert [c["name"] for c in ctrs] == ["prometheus",
                                         "stackdriver-sidecar"]
    # the sidecar tails the WAL: both containers share /prometheus
    for c in ctrs:
        assert {"name": "data", "mountPath": "/prometheus"} in \
            c["volumeMounts"]
    vols = {v["name"] for v in deploy["spec"]["template"]["spec"]["volumes"]}
    assert vols == {"config", "data"}
    assert "--storage.tsdb.path=/prometheus" in ctrs[0]["args"]


def test_nfs_storage_component(config):
    objs = render_component(config, ComponentSpec("nfs-storage", params={
        "server_ip": "10.0.0.2"}))
    pv, pvc = objs
    assert pv["kind"] == "PersistentVolume"
    assert pv["spec"]["nfs"] == {"path": "/shared", "server": "10.0.0.2"}
    assert pv["spec"]["accessModes"] == ["ReadWriteMany"]
    assert pvc["kind"] == "PersistentVolumeClaim"
    assert pvc["spec"]["storageClassName"] == "nfs-storage"
    with pytest.raises(ValueError, match="server_ip"):
        render_component(config, ComponentSpec("nfs-storage"))
