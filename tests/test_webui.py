"""Browser UX tier: static frontends served next to the JSON APIs."""

import json
import os
import re
import urllib.error
import urllib.request

import pytest

from kubeflow_tpu.k8s import FakeKubeClient
from kubeflow_tpu.utils.jsonhttp import serve_json

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _get(url):
    try:
        with urllib.request.urlopen(url, timeout=10) as resp:
            return resp.status, resp.read(), resp.headers.get("Content-Type")
    except urllib.error.HTTPError as e:
        return e.code, e.read(), e.headers.get("Content-Type")


@pytest.fixture
def dashboard_server():
    from kubeflow_tpu.dashboard.server import DashboardApi

    from kubeflow_tpu.tenancy.authz import allow_all

    client = FakeKubeClient()
    client.create({"apiVersion": "v1", "kind": "Namespace",
                   "metadata": {"name": "kubeflow"}})
    api = DashboardApi(client, authorize=allow_all)  # page-serving fixture
    srv = serve_json(
        api.handle, 0, background=True, host="127.0.0.1",
        static_dir=os.path.join(REPO, "kubeflow_tpu/dashboard/static"))
    yield f"http://127.0.0.1:{srv.server_address[1]}"
    srv.shutdown()


def test_dashboard_serves_ui_and_api(dashboard_server):
    code, body, ctype = _get(dashboard_server + "/")
    assert code == 200 and b"<html" in body and "text/html" in ctype
    code, body, ctype = _get(dashboard_server + "/app.js")
    assert code == 200 and "javascript" in ctype
    code, body, ctype = _get(dashboard_server + "/style.css")
    assert code == 200 and "css" in ctype
    code, body, _ = _get(dashboard_server + "/login.html")
    assert code == 200 and b"login-form" in body
    # API still routes
    code, body, ctype = _get(dashboard_server + "/api/env-info")
    assert code == 200 and "json" in ctype
    assert json.loads(body)["namespaces"] == ["kubeflow"]


def test_dashboard_serves_studies_and_runs_pages(dashboard_server):
    for page, marker in (("/studies.html", b"objective-chart"),
                         ("/runs.html", b"Workflow Runs"),
                         ("/tpujobs.html", b"TPU Jobs"),
                         ("/studies.js", b"drawChart"),
                         ("/runs.js", b"loadRuns"),
                         ("/tpujobs.js", b"loadJobs")):
        code, body, _ = _get(dashboard_server + page)
        assert code == 200 and marker in body, page
    # the API routes the pages consume exist (empty namespace → empty lists)
    code, body, _ = _get(dashboard_server + "/api/studies/kubeflow")
    assert code == 200 and json.loads(body) == []
    code, body, _ = _get(dashboard_server + "/api/runs/kubeflow")
    assert code == 200 and json.loads(body) == []
    code, body, _ = _get(dashboard_server + "/api/tpujobs/kubeflow")
    assert code == 200 and json.loads(body) == []


def test_dashboard_static_traversal_blocked(dashboard_server):
    code, _, _ = _get(dashboard_server + "/../../etc/passwd")
    assert code == 404
    code, _, _ = _get(dashboard_server + "/%2e%2e/%2e%2e/etc/passwd")
    assert code == 404


def test_webapp_serves_notebook_manager():
    from kubeflow_tpu.notebooks.webapp import NotebookWebApp, serve

    client = FakeKubeClient()
    client.create({"apiVersion": "v1", "kind": "Namespace",
                   "metadata": {"name": "kubeflow"}})
    srv = serve(NotebookWebApp(client), port=0, background=True)
    try:
        base = f"http://{srv.server_address[0]}:{srv.server_address[1]}"
        code, body, _ = _get(base + "/")
        assert code == 200 and b"Notebooks" in body
        code, body, _ = _get(base + "/notebooks.js")
        assert code == 200
        code, body, _ = _get(base + "/api/namespaces")
        assert json.loads(body)["namespaces"] == ["kubeflow"]
    finally:
        srv.shutdown()


def test_bootstrap_serves_deploy_ui(tmp_path):
    from kubeflow_tpu.bootstrap.server import DeployServer

    server = DeployServer(FakeKubeClient(), app_root=str(tmp_path))
    srv = serve_json(
        server.handle, 0, background=True, host="127.0.0.1",
        static_dir=os.path.join(REPO, "kubeflow_tpu/bootstrap/static"))
    try:
        base = f"http://127.0.0.1:{srv.server_address[1]}"
        code, body, _ = _get(base + "/")
        assert code == 200 and b"deploy-form" in body
        code, body, _ = _get(base + "/healthz")
        assert code == 200 and json.loads(body) == {"ok": True}
    finally:
        srv.shutdown()


def test_applications_health_route():
    """/api/applications/<ns> surfaces Application CR aggregate status
    (the reference's grouped-health concept, application.libsonnet)."""
    from kubeflow_tpu.dashboard.server import DashboardApi
    from kubeflow_tpu.operators.application import (
        ApplicationController,
        application,
    )
    from kubeflow_tpu.k8s import objects as o
    from kubeflow_tpu.manifests.registry import PART_OF_LABEL

    client = FakeKubeClient()
    sel = {PART_OF_LABEL: "demo"}
    dep = o.deployment("web", "kubeflow",
                       o.pod_spec([o.container("c", "i")]),
                       replicas=2, labels={"app": "web", **sel})
    dep["status"] = {"readyReplicas": 1}
    client.create(dep)
    client.create(application("demo", "kubeflow", selector=sel))
    ApplicationController(client).reconcile("kubeflow", "demo")

    api = DashboardApi(client, authorize=lambda *a: True)
    code, apps = api.handle("GET", "/api/applications/kubeflow", None,
                            "alice")
    assert code == 200
    # `ready` counts components (this 1 Deployment is 1/2-rolled-out, so
    # not ready), not replicas
    assert apps == [{"name": "demo", "phase": "Progressing",
                     "ready": "0/1", "failing": ["Deployment/web"]}]


def test_namespaced_routes_reject_empty_namespace():
    """An empty trailing ns segment must 404, not become a cluster-wide
    list (cross-tenant leak through the client layer)."""
    from kubeflow_tpu.dashboard.server import DashboardApi

    api = DashboardApi(FakeKubeClient(), authorize=lambda *a: True)
    for path in ("/api/applications/", "/api/activities/",
                 "/api/tpujobs/", "/api/studies/", "/api/runs/"):
        code, _ = api.handle("GET", path, None, "alice")
        assert code == 404, path


def test_static_served_without_auth_but_api_guarded():
    """login.html must stay reachable when cookie auth is on; the API not."""
    from kubeflow_tpu.auth.gatekeeper import cookie_authenticator
    from kubeflow_tpu.dashboard.server import DashboardApi

    api = DashboardApi(FakeKubeClient())
    srv = serve_json(
        api.handle, 0, background=True, host="127.0.0.1",
        authenticator=cookie_authenticator(b"secret"),
        static_dir=os.path.join(REPO, "kubeflow_tpu/dashboard/static"))
    try:
        base = f"http://127.0.0.1:{srv.server_address[1]}"
        code, _, _ = _get(base + "/login.html")
        assert code == 200
        code, _, _ = _get(base + "/style.css")
        assert code == 200  # login page's stylesheet is public too
        code, _, _ = _get(base + "/api/env-info")
        assert code == 401
        # non-public static is gated: browser gets bounced to login
        opener = urllib.request.build_opener(_NoRedirect)
        try:
            opener.open(base + "/app.js", timeout=10)
            raise AssertionError("expected 302")
        except urllib.error.HTTPError as e:
            assert e.code == 302
            assert e.headers["Location"].startswith("/login.html")
        # with a valid cookie the app shell serves
        from kubeflow_tpu.auth.gatekeeper import AuthServer

        cookie = AuthServer({}, b"secret").issue_cookie("alice")
        req = urllib.request.Request(
            base + "/app.js", headers={"Cookie": f"kftpu-auth={cookie}"})
        with urllib.request.urlopen(req, timeout=10) as resp:
            assert resp.status == 200
    finally:
        srv.shutdown()


class _NoRedirect(urllib.request.HTTPRedirectHandler):
    def redirect_request(self, *a, **k):
        return None


def test_html_references_resolve():
    """Every src/href in the shipped pages points at a shipped file."""
    static_dirs = [
        os.path.join(REPO, "kubeflow_tpu", d, "static")
        for d in ("dashboard", "notebooks", "bootstrap")
    ]
    for sdir in static_dirs:
        for fname in os.listdir(sdir):
            if not fname.endswith(".html"):
                continue
            html = open(os.path.join(sdir, fname)).read()
            for ref in re.findall(r'(?:src|href)="([^"]+)"', html):
                if ref.startswith(("http", "#", "/")):
                    ref = ref.lstrip("/")
                if not ref or "{" in ref:
                    continue
                assert os.path.isfile(os.path.join(sdir, ref)), \
                    f"{fname} references missing asset {ref!r} in {sdir}"


def test_run_detail_dag_and_artifacts(tmp_path):
    """Run drill-down (VERDICT r3 #6): the detail API carries the step
    DAG inputs (spec.steps dependencies + per-step phases) and the
    run's artifacts; artifact files download raw through the dashboard."""
    from kubeflow_tpu.dashboard.server import DashboardApi
    from kubeflow_tpu.utils.jsonhttp import RawResponse
    from kubeflow_tpu.workflows.archive import ArtifactStore
    from kubeflow_tpu.workflows.workflow import (
        WORKFLOW_API_VERSION,
        WORKFLOW_KIND,
    )

    client = FakeKubeClient()
    client.create({
        "apiVersion": WORKFLOW_API_VERSION, "kind": WORKFLOW_KIND,
        "metadata": {"name": "r1", "namespace": "team-a", "uid": "u1"},
        "spec": {"steps": [
            {"name": "setup"},
            {"name": "train", "dependencies": ["setup"]},
            {"name": "eval", "dependencies": ["train"]}]},
        "status": {"phase": "Running", "nodes": {
            "setup": {"phase": "Succeeded"},
            "train": {"phase": "Running"}}}})
    store = ArtifactStore(str(tmp_path))
    store.put("team-a", "r1", "train", "metrics.json", b'{"loss": 1}')
    api = DashboardApi(client, artifact_store=store,
                       authorize=lambda *a: True)

    code, d = api.handle("GET", "/api/runs/team-a/r1", None, "u")
    assert code == 200
    assert [s["name"] for s in d["spec"]["steps"]] == [
        "setup", "train", "eval"]
    assert d["artifacts"] == [
        {"step": "train", "name": "metrics.json", "bytes": 11}]

    code, arts = api.handle("GET", "/api/artifacts/team-a/r1", None, "u")
    assert code == 200 and arts[0]["name"] == "metrics.json"
    code, raw = api.handle(
        "GET", "/api/artifacts/team-a/r1/train/metrics.json", None, "u")
    assert code == 200 and isinstance(raw, RawResponse)
    # large artifacts stream from disk: the response carries a path
    assert raw.data is None
    with open(raw.path, "rb") as f:
        assert f.read() == b'{"loss": 1}'
    assert raw.content_type == "application/json"
    code, _ = api.handle(
        "GET", "/api/artifacts/team-a/r1/train/nope.bin", None, "u")
    assert code == 404

    # artifact routes are namespace-guarded like runs
    denied = DashboardApi(client, artifact_store=store,
                          authorize=lambda *a: False)
    code, _ = denied.handle(
        "GET", "/api/artifacts/team-a/r1", None, "mallory")
    assert code == 403


def test_artifact_download_over_http(tmp_path):
    """RawResponse serves bytes end-to-end through serve_json."""
    from kubeflow_tpu.dashboard.server import DashboardApi
    from kubeflow_tpu.workflows.archive import ArtifactStore

    from kubeflow_tpu.tenancy.authz import allow_all

    store = ArtifactStore(str(tmp_path))
    store.put("ns1", "run1", "train", "model.bin", b"\x00\x01binary")
    api = DashboardApi(FakeKubeClient(), artifact_store=store,
                       authorize=allow_all)
    srv = serve_json(api.handle, 0, background=True, host="127.0.0.1")
    try:
        url = (f"http://127.0.0.1:{srv.server_address[1]}"
               "/api/artifacts/ns1/run1/train/model.bin")
        code, body, ctype = _get(url)
        assert code == 200 and body == b"\x00\x01binary"
        assert "octet-stream" in ctype
    finally:
        srv.shutdown()


def test_runs_page_ships_dag_and_artifact_views(dashboard_server):
    code, body, _ = _get(dashboard_server + "/runs.html")
    assert code == 200
    assert b'id="dag"' in body and b'id="artifacts"' in body
    code, body, _ = _get(dashboard_server + "/runs.js")
    assert b"drawDag" in body and b"/api/artifacts/" in body
    code, body, _ = _get(dashboard_server + "/models.js")
    assert b"drawLineage" in body and b"lineage-chain" in body


def test_nested_artifact_steps_roundtrip(tmp_path):
    """Checkpoint trees produce nested step relpaths; list() entries must
    resolve through the download route (percent-encoded step)."""
    from urllib.parse import quote

    from kubeflow_tpu.dashboard.server import DashboardApi
    from kubeflow_tpu.workflows.archive import ArtifactStore

    store = ArtifactStore(str(tmp_path))
    store.put("ns1", "r1", "train/ckpt-1000", "data-0", b"weights")
    api = DashboardApi(FakeKubeClient(), artifact_store=store,
                       authorize=lambda *a: True)
    arts = store.list("ns1", "r1")
    assert arts == [{"step": "train/ckpt-1000", "name": "data-0",
                     "bytes": 7}]
    url = ("/api/artifacts/ns1/r1/" +
           quote(arts[0]["step"], safe="") + "/" + arts[0]["name"])
    code, raw = api.handle("GET", url, None, "u")
    assert code == 200
    with open(raw.path, "rb") as f:
        assert f.read() == b"weights"
    # traversal segments are stripped, never escape the store
    code, _ = api.handle(
        "GET", "/api/artifacts/ns1/r1/" + quote("../../ns2", safe="") +
        "/data-0", None, "u")
    assert code == 404
