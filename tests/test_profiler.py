"""XLA trace capture tier (SURVEY §5): traces land on disk, env contract."""

import os

import jax
import jax.numpy as jnp

from kubeflow_tpu.utils.profiler import StepProfiler, trace


def _has_trace(d):
    for root, _, files in os.walk(d):
        if any(f.endswith((".xplane.pb", ".trace.json.gz")) for f in files):
            return True
    return False


def test_trace_context_manager_writes_trace(tmp_path):
    logdir = str(tmp_path / "trace")
    f = jax.jit(lambda x: x @ x)
    x = jnp.ones((64, 64))
    with trace(logdir):
        f(x).block_until_ready()
    assert _has_trace(logdir), os.listdir(logdir)


def test_step_profiler_captures_window(tmp_path):
    logdir = str(tmp_path / "steps")
    prof = StepProfiler(logdir, start=2, n_steps=2)
    f = jax.jit(lambda x: x * 2)
    x = jnp.ones((8,))
    for step in range(6):
        prof.step(step)
        f(x).block_until_ready()
    prof.close()
    assert _has_trace(logdir)


def test_step_profiler_disabled_is_noop(tmp_path):
    prof = StepProfiler(None)
    for step in range(5):
        prof.step(step)
    prof.close()  # nothing raised, nothing written


def test_step_profiler_from_env(monkeypatch, tmp_path):
    monkeypatch.setenv("KFTPU_PROFILE_DIR", str(tmp_path / "envtrace"))
    monkeypatch.setenv("KFTPU_PROFILE_START", "0")
    monkeypatch.setenv("KFTPU_PROFILE_STEPS", "1")
    prof = StepProfiler.from_env()
    assert prof.enabled and prof.start == 0 and prof.stop == 1


def test_step_profiler_from_env_malformed_window(monkeypatch, tmp_path,
                                                 caplog):
    """A typo'd window env var must not crash worker 0 at boot — the
    profiler warns and comes up disabled (the training job matters
    more than its trace)."""
    import logging

    monkeypatch.setenv("KFTPU_PROFILE_DIR", str(tmp_path / "t"))
    monkeypatch.setenv("KFTPU_PROFILE_START", "ten")
    monkeypatch.setenv("KFTPU_PROFILE_STEPS", "3")
    with caplog.at_level(logging.WARNING):
        prof = StepProfiler.from_env()
    assert not prof.enabled
    assert any("KFTPU_PROFILE_START" in r.message for r in caplog.records)
    for step in range(3):
        prof.step(step)  # still a safe no-op
    prof.close()

    monkeypatch.setenv("KFTPU_PROFILE_START", "2")
    monkeypatch.setenv("KFTPU_PROFILE_STEPS", "2.5")  # int() rejects
    with caplog.at_level(logging.WARNING):
        prof = StepProfiler.from_env()
    assert not prof.enabled


def test_step_profiler_from_env_malformed_without_dir(monkeypatch,
                                                      caplog):
    """Malformed window vars with no profile dir at all: still no
    crash, still disabled."""
    import logging

    monkeypatch.delenv("KFTPU_PROFILE_DIR", raising=False)
    monkeypatch.setenv("KFTPU_PROFILE_START", "")
    monkeypatch.setenv("KFTPU_PROFILE_STEPS", "-")
    with caplog.at_level(logging.WARNING):
        prof = StepProfiler.from_env()
    assert not prof.enabled


def _write_fake_trace(d, run="run1"):
    """Synthesize the profiler's trace.json.gz layout: one device pid
    with an 'XLA Ops' lane plus a host pid that must be ignored."""
    import gzip
    import json

    pdir = os.path.join(d, "plugins", "profile", run)
    os.makedirs(pdir, exist_ok=True)
    events = [
        {"ph": "M", "pid": 3, "name": "process_name",
         "args": {"name": "/device:TPU:0"}},
        {"ph": "M", "pid": 3, "tid": 3, "name": "thread_name",
         "args": {"name": "XLA Ops"}},
        {"ph": "M", "pid": 3, "tid": 1, "name": "thread_name",
         "args": {"name": "Steps"}},
        {"ph": "M", "pid": 9, "name": "process_name",
         "args": {"name": "/host:CPU"}},
        {"ph": "M", "pid": 9, "tid": 1, "name": "thread_name",
         "args": {"name": "XLA Ops"}},
        {"ph": "X", "pid": 3, "tid": 3, "name": "fusion.1",
         "ts": 0, "dur": 300.0},
        {"ph": "X", "pid": 3, "tid": 3, "name": "fusion.1",
         "ts": 400, "dur": 100.0},
        {"ph": "X", "pid": 3, "tid": 3, "name": "copy.2",
         "ts": 600, "dur": 100.0},
        {"ph": "X", "pid": 3, "tid": 1, "name": "step 0",
         "ts": 0, "dur": 700.0},
        # host-lane event with a device-like name: must not count
        {"ph": "X", "pid": 9, "tid": 1, "name": "fusion.1",
         "ts": 0, "dur": 9999.0},
    ]
    path = os.path.join(pdir, "vm.trace.json.gz")
    with gzip.open(path, "wt") as f:
        json.dump({"traceEvents": events}, f)
    return path


def test_trace_top_aggregates_device_ops(tmp_path):
    from kubeflow_tpu.bench.trace_tools import format_top_ops, top_ops

    _write_fake_trace(str(tmp_path))
    report = top_ops(str(tmp_path), top=5)
    assert report["devices"] == ["/device:TPU:0"]
    assert report["steps"] == 1
    assert report["device_total_ms"] == 0.5
    ops = {o["name"]: o for o in report["ops"]}
    assert ops["fusion.1"]["total_ms"] == 0.4
    assert ops["fusion.1"]["count"] == 2
    assert ops["fusion.1"]["pct"] == 80.0
    assert ops["copy.2"]["pct"] == 20.0
    table = format_top_ops(report)
    assert "fusion.1" in table and "80.0" in table


def test_trace_top_cli(tmp_path, capsys):
    import json

    from kubeflow_tpu.cli.main import main as ctl_main

    _write_fake_trace(str(tmp_path))
    assert ctl_main(["trace-top", str(tmp_path), "--json"]) == 0
    out = json.loads(capsys.readouterr().out)
    assert out["ops"][0]["name"] == "fusion.1"
    assert ctl_main(["trace-top", str(tmp_path / "missing")]) == 1
