"""XLA trace capture tier (SURVEY §5): traces land on disk, env contract."""

import os

import jax
import jax.numpy as jnp

from kubeflow_tpu.utils.profiler import StepProfiler, trace


def _has_trace(d):
    for root, _, files in os.walk(d):
        if any(f.endswith((".xplane.pb", ".trace.json.gz")) for f in files):
            return True
    return False


def test_trace_context_manager_writes_trace(tmp_path):
    logdir = str(tmp_path / "trace")
    f = jax.jit(lambda x: x @ x)
    x = jnp.ones((64, 64))
    with trace(logdir):
        f(x).block_until_ready()
    assert _has_trace(logdir), os.listdir(logdir)


def test_step_profiler_captures_window(tmp_path):
    logdir = str(tmp_path / "steps")
    prof = StepProfiler(logdir, start=2, n_steps=2)
    f = jax.jit(lambda x: x * 2)
    x = jnp.ones((8,))
    for step in range(6):
        prof.step(step)
        f(x).block_until_ready()
    prof.close()
    assert _has_trace(logdir)


def test_step_profiler_disabled_is_noop(tmp_path):
    prof = StepProfiler(None)
    for step in range(5):
        prof.step(step)
    prof.close()  # nothing raised, nothing written


def test_step_profiler_from_env(monkeypatch, tmp_path):
    monkeypatch.setenv("KFTPU_PROFILE_DIR", str(tmp_path / "envtrace"))
    monkeypatch.setenv("KFTPU_PROFILE_START", "0")
    monkeypatch.setenv("KFTPU_PROFILE_STEPS", "1")
    prof = StepProfiler.from_env()
    assert prof.enabled and prof.start == 0 and prof.stop == 1
