"""Serving tests: model store round-trip + live HTTP server (the reference's
serving test pattern: gRPC PredictRequest vs golden with tolerance,
``testing/test_tf_serving.py:40-57`` — here REST against a real socket)."""

import json
import urllib.error
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubeflow_tpu.models import MnistCnn
from kubeflow_tpu.serving import ModelServer, export_model, load_latest


@pytest.fixture(scope="module")
def mnist_params():
    model = MnistCnn()
    return model, model.init(jax.random.key(0),
                             jnp.zeros((1, 28, 28, 1)))["params"]


@pytest.fixture
def repo(tmp_path, mnist_params):
    model, params = mnist_params
    export_model(str(tmp_path / "mnist"), "mnist", params, version=1)
    return tmp_path


def _post(url, payload):
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"}, method="POST")
    with urllib.request.urlopen(req, timeout=30) as resp:
        return resp.status, json.loads(resp.read())


def _get(url):
    with urllib.request.urlopen(url, timeout=30) as resp:
        return resp.status, json.loads(resp.read())


def test_store_roundtrip(tmp_path, mnist_params):
    model, params = mnist_params
    export_model(str(tmp_path / "m"), "mnist", params, version=3)
    loaded = load_latest(str(tmp_path / "m"))
    assert loaded.version == 3
    x = jnp.ones((2, 28, 28, 1))
    np.testing.assert_allclose(
        np.asarray(loaded.predict(x)),
        np.asarray(model.apply({"params": params}, x)),
        atol=1e-5,
    )


def test_server_predict_end_to_end(repo, mnist_params):
    model, params = mnist_params
    server = ModelServer(str(repo), port=0, poll_interval_s=0.2)
    port = server.start()
    try:
        # golden comparison with numeric tolerance
        x = np.random.RandomState(0).randn(2, 28, 28, 1).astype(np.float32)
        code, body = _post(
            f"http://127.0.0.1:{port}/v1/models/mnist:predict",
            {"instances": x.tolist()})
        assert code == 200
        expected = np.asarray(model.apply({"params": params}, jnp.asarray(x)))
        np.testing.assert_allclose(np.asarray(body["predictions"]), expected,
                                   atol=1e-4)
        assert body["model_version"] == "1"

        code, body = _get(f"http://127.0.0.1:{port}/v1/models")
        assert body["models"] == ["mnist"]
        code, body = _get(f"http://127.0.0.1:{port}/v1/models/mnist")
        assert body["model_version_status"][0]["state"] == "AVAILABLE"
    finally:
        server.stop()


def test_server_version_hot_reload(repo, mnist_params):
    model, params = mnist_params
    server = ModelServer(str(repo), port=0, poll_interval_s=0.1)
    port = server.start()
    try:
        zero_params = jax.tree_util.tree_map(jnp.zeros_like, params)
        export_model(str(repo / "mnist"), "mnist", zero_params, version=2)
        import time

        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            _, body = _post(
                f"http://127.0.0.1:{port}/v1/models/mnist:predict",
                {"instances": np.zeros((1, 28, 28, 1)).tolist()})
            if body.get("model_version") == "2":
                break
            time.sleep(0.1)
        assert body["model_version"] == "2"
    finally:
        server.stop()


def test_server_error_paths(repo):
    server = ModelServer(str(repo), port=0)
    port = server.start()
    try:
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post(f"http://127.0.0.1:{port}/v1/models/nope:predict",
                  {"instances": [[0.0]]})
        assert ei.value.code == 404
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post(f"http://127.0.0.1:{port}/v1/models/mnist:predict",
                  {"wrong": 1})
        assert ei.value.code == 400
        # oversized batch
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post(f"http://127.0.0.1:{port}/v1/models/mnist:predict",
                  {"instances": np.zeros((64, 28, 28, 1)).tolist()})
        assert ei.value.code == 400
        # version pin to a missing version
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post(f"http://127.0.0.1:{port}/v1/models/mnist/versions/9:predict",
                  {"instances": np.zeros((1, 28, 28, 1)).tolist()})
        assert ei.value.code == 404
    finally:
        server.stop()


def test_padding_keeps_one_compiled_shape(repo):
    """Odd batch sizes bucket up to fixed shapes (no per-request recompiles)."""
    server = ModelServer(str(repo), port=0, max_batch_size=8)
    port = server.start()
    try:
        for n in (1, 3, 5):
            code, body = _post(
                f"http://127.0.0.1:{port}/v1/models/mnist:predict",
                {"instances": np.zeros((n, 28, 28, 1)).tolist()})
            assert code == 200
            assert len(body["predictions"]) == n
    finally:
        server.stop()


def test_scalar_instances_clean_400(repo):
    server = ModelServer(str(repo), port=0)
    port = server.start()
    try:
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post(f"http://127.0.0.1:{port}/v1/models/mnist:predict",
                  {"instances": 5})
        assert ei.value.code == 400
    finally:
        server.stop()


def test_pinned_version_served_and_cached(repo, mnist_params):
    model, params = mnist_params
    from kubeflow_tpu.serving import export_model
    import jax, jax.numpy as jnp

    zero = jax.tree_util.tree_map(jnp.zeros_like, params)
    export_model(str(repo / "mnist"), "mnist", zero, version=2)
    server = ModelServer(str(repo), port=0, poll_interval_s=60)
    server.repo.refresh()
    port = server.start()
    try:
        x = np.zeros((1, 28, 28, 1)).tolist()
        # latest is 2; pin 1
        _, body = _post(f"http://127.0.0.1:{port}/v1/models/mnist/versions/1:predict",
                        {"instances": x})
        assert body["model_version"] == "1"
        assert ("mnist", 1) in server.repo._pinned  # cached for next time
    finally:
        server.stop()
