"""Echo server tests (kubeflow/common echo-server parity)."""

import json
import urllib.request

from kubeflow_tpu.config.deployment import ComponentSpec, DeploymentConfig
from kubeflow_tpu.manifests.registry import render_component
from kubeflow_tpu.utils.echo import EchoService
from kubeflow_tpu.utils.jsonhttp import serve_json


def test_echo_reflects_request_over_live_socket():
    httpd = serve_json(EchoService().handle, 0, background=True)
    try:
        port = httpd.server_address[1]
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/some/route?x=1",
            data=json.dumps({"hello": "world"}).encode(),
            headers={"Content-Type": "application/json",
                     "X-Kubeflow-Userid": "alice"})
        with urllib.request.urlopen(req, timeout=10) as resp:
            out = json.load(resp)
        assert out["method"] == "POST"
        assert out["path"] == "/some/route?x=1"
        assert out["body"] == {"hello": "world"}
        assert out["user"] == "alice"
        assert "X-Kubeflow-Userid" in out["headers"]
    finally:
        httpd.shutdown()


def test_echo_component_golden():
    cfg = DeploymentConfig(name="d", platform="local",
                           components=[ComponentSpec("echo-server")])
    objs = render_component(cfg, cfg.components[0])
    assert [o["kind"] for o in objs] == ["Deployment", "Service"]
    cmd = objs[0]["spec"]["template"]["spec"]["containers"][0]["command"]
    assert cmd == ["python", "-m", "kubeflow_tpu.utils.echo"]
