"""Cluster scheduler plane: predictive gang queue, contention-aware
placement, checkpoint-preempt-requeue — all deterministic on FakeClock +
FakeKubeClient (docs/SCHEDULER.md)."""

import threading
import time

import numpy as np
import pytest

from kubeflow_tpu.k8s import FakeKubeClient
from kubeflow_tpu.manifests.components.tpujob_operator import (
    API_VERSION,
    TPUJOB_KIND,
)
from kubeflow_tpu.obs.steps import tpujob_trace_ids
from kubeflow_tpu.obs.trace import SpanCollector, Tracer
from kubeflow_tpu.operators.tpujob import (
    JOB_LABEL,
    PHASE_PENDING,
    PHASE_SUCCEEDED,
    PreemptionCheckpointer,
    TpuJobOperator,
    tpujob,
)
from kubeflow_tpu.platform.local import fake_slice_nodes
from kubeflow_tpu.scheduler.contention import (
    choose_slices_contended,
    link_load,
    window_contention,
)
from kubeflow_tpu.scheduler.inventory import (
    ASSIGNED_SLICE_LABEL,
    SHAPE_LABEL,
    SLICE_INDEX_LABEL,
    choose_slices,
    choose_slices_py,
)
from kubeflow_tpu.scheduler.predictor import ThroughputPredictor, shape_factor
from kubeflow_tpu.scheduler.queue import (
    BLOCKED,
    PLACED,
    PREEMPTING,
    QUEUED,
    GangQueue,
    GangRequest,
)
from kubeflow_tpu.utils import DEFAULT_REGISTRY


class FakeClock:
    """Thread-safe tick clock: every read advances ``step``."""

    def __init__(self, start: float = 1000.0, step: float = 0.5):
        self.t = start
        self.step = step
        self._lock = threading.Lock()

    def __call__(self) -> float:
        with self._lock:
            self.t += self.step
            return self.t


def _gang(ns, name, *, slices=1, hosts=2, priority=0, preemptible=True,
          total_steps=None, accelerator="v5e-8", uid="", min_slices=None):
    return GangRequest(namespace=ns, name=name, slices=slices,
                       hosts_per_slice=hosts, chips_per_host=4,
                       accelerator=accelerator, priority=priority,
                       preemptible=preemptible, total_steps=total_steps,
                       uid=uid, min_slices=min_slices)


def _quota(client, ns, chips):
    client.create({"apiVersion": "v1", "kind": "ResourceQuota",
                   "metadata": {"name": "profile-quota", "namespace": ns},
                   "spec": {"hard": {"google.com/tpu": str(chips)}}})


def _seed(client, shape="v5e-8", count=4):
    for node in fake_slice_nodes(shape, count=count):
        client.create(node)


def make_queue(client, **kw):
    kw.setdefault("clock", FakeClock())
    kw.setdefault("tracer", Tracer(SpanCollector(), clock=kw["clock"]))
    return GangQueue(client, **kw)


# -- contention scoring ------------------------------------------------------


def test_link_load_and_window_contention():
    # gangs on [0,3] and [2,4]: links 0-2 loaded once, links 2-3 shared
    load = link_load([(0, 3), (2, 4)], 6)
    assert load == [1, 1, 2, 1, 0]
    assert window_contention(load, 1, 2) == 1
    assert window_contention(load, 4, 5) == 0
    assert window_contention(load, 0, 0) == 0      # single-slice: ICI only
    assert window_contention(load, 3, 1) == 3      # reversed bounds ok


def test_contended_choice_prefers_uncontended_window():
    # slices: [0]=4h, [1]=2h, [2]=2h, [3..5]=4h; a 2-slice gang already
    # rides links 0..2 (window [0,3]); the tight [1,2] window would
    # share its links — the scorer must pay waste to take [4,5]
    hosts = [4, 2, 2, 4, 4, 4]
    free = [0, 2, 2, 0, 4, 4]
    load = link_load([(0, 3)], 6)
    baseline = choose_slices_py(hosts, free, 2, 2)
    assert baseline == [1, 2]                       # waste-first ranking
    contended = choose_slices_contended(hosts, free, 2, 2, load)
    assert contended == [4, 5]                      # uncontended wins


def test_contended_zero_load_delegates_to_twin():
    import random

    rng = random.Random(7)
    for _ in range(100):
        n = rng.randint(1, 12)
        hosts = [rng.choice([1, 2, 4]) for _ in range(n)]
        free = [rng.choice([0, h]) for h in hosts]
        want, need = rng.randint(1, 3), rng.choice([1, 2, 4])
        expect = choose_slices(hosts, free, want, need)
        assert choose_slices_contended(hosts, free, want, need) == expect
        assert choose_slices_contended(hosts, free, want, need,
                                       [0] * (n - 1)) == expect


# -- predictor ---------------------------------------------------------------


def test_predictor_absent_never_wrong():
    p = ThroughputPredictor(clock=FakeClock())
    assert p.estimate("d", "j") is None
    assert p.remaining_seconds("d", "j", total_steps=100) is None
    # zero-rate telemetry carries no signal and must not create one
    p.observe("d", "j", steps_per_sec=0.0, last_step=5)
    assert p.estimate("d", "j") is None


def test_predictor_rate_and_remaining():
    p = ThroughputPredictor(clock=FakeClock())
    p.observe("d", "j", steps_per_sec=2.0, last_step=100)
    est = p.estimate("d", "j", total_steps=300)
    assert est.source == "job"
    assert est.steps_per_sec == pytest.approx(2.0)
    assert est.remaining_steps == 200
    assert est.remaining_seconds == pytest.approx(100.0)
    # online correction: the EWMA folds a faster reading in
    p.observe("d", "j", steps_per_sec=4.0, last_step=120)
    est = p.estimate("d", "j", total_steps=300)
    assert 2.0 < est.steps_per_sec < 4.0
    # total_steps unknown -> rate known, remaining honestly absent
    est = p.estimate("d", "j")
    assert est.remaining_seconds is None


def test_predictor_class_baseline_for_new_jobs():
    p = ThroughputPredictor(clock=FakeClock())
    p.observe("d", "seen", steps_per_sec=3.0, last_step=50,
              accelerator="v5e-8", slices=1)
    est = p.estimate("d", "new", total_steps=60, accelerator="v5e-8",
                     slices=2)
    assert est is not None and est.source == "class"
    assert est.steps_per_sec == pytest.approx(
        3.0 * shape_factor(1) / shape_factor(2))
    # a different accelerator class learned nothing
    assert p.estimate("d", "other", accelerator="v5p-8") is None


def test_predictor_stale_observation_ignored():
    clock = FakeClock(step=0.0)
    p = ThroughputPredictor(clock=clock, ttl_s=60.0)
    p.observe("d", "j", steps_per_sec=2.0, last_step=10)
    clock.t += 3600.0
    assert p.estimate("d", "j", total_steps=100) is None


# -- queue: admission, ordering, placement -----------------------------------


def test_quota_admission_blocks_and_readmits():
    client = FakeKubeClient()
    _seed(client, count=4)
    _quota(client, "tenant", 16)            # two 8-chip gangs
    q = make_queue(client)
    assert q.submit(_gang("tenant", "a")) == QUEUED
    assert q.submit(_gang("tenant", "b")) == QUEUED
    assert q.submit(_gang("tenant", "c")) == BLOCKED
    assert "quota 16 exceeded" in q.blocked_reason("tenant", "c")
    # another namespace is not gated by this tenant's quota
    assert q.submit(_gang("prod", "p")) == QUEUED
    q.schedule()
    assert q.state_of("tenant", "c") == BLOCKED
    # a sibling finishing frees quota; the next cycle re-admits
    q.release("tenant", "a")
    q.schedule()
    assert q.state_of("tenant", "c") == PLACED


def test_priority_then_predicted_then_fifo_ordering():
    client = FakeKubeClient()
    _seed(client, count=1)                  # one slice: strict ordering
    q = make_queue(client)
    q.predictor.observe("d", "long", steps_per_sec=1.0, last_step=0)
    q.predictor.observe("d", "short", steps_per_sec=1.0, last_step=900)
    q.submit(_gang("d", "unknown", total_steps=None))   # FIFO tail
    q.submit(_gang("d", "long", total_steps=1000))
    q.submit(_gang("d", "short", total_steps=1000))
    q.submit(_gang("d", "vip", priority=5))
    q.schedule()
    placed = [g["name"] for g in q.status()["gangs"]
              if g["state"] == PLACED]
    assert placed == ["vip"]                # priority class dominates
    q.release("d", "vip")
    q.schedule()
    assert q.state_of("d", "short") == PLACED   # SRF within the class
    q.release("d", "short")
    q.schedule()
    assert q.state_of("d", "long") == PLACED    # predicted before unknown
    q.release("d", "long")
    q.schedule()
    assert q.state_of("d", "unknown") == PLACED


def test_queue_wait_and_depth_metrics_move():
    client = FakeKubeClient()
    _seed(client, count=1)
    depth = DEFAULT_REGISTRY.gauge("kftpu_queue_depth")
    wait_h = DEFAULT_REGISTRY.histogram("kftpu_queue_wait_seconds")
    waits_before = wait_h.get()
    q = make_queue(client)
    q.submit(_gang("d", "a"))
    q.submit(_gang("d", "b"))
    q.schedule()
    assert depth.get(state=PLACED) == 1
    assert depth.get(state=QUEUED) == 1
    assert wait_h.get() == waits_before + 1


def test_placement_atomic_or_not_at_all():
    client = FakeKubeClient()
    _seed(client, count=2)
    q = make_queue(client)
    q.submit(_gang("d", "big", slices=3))   # needs 3, cluster has 2
    q.schedule()
    assert q.state_of("d", "big") == QUEUED
    assert q.placement_for("d", "big") is None


def test_empty_inventory_places_unpinned():
    q = make_queue(FakeKubeClient())        # no nodes at all
    q.submit(_gang("d", "j"))
    q.schedule()
    assert q.placement_for("d", "j") == []  # placed, selector-only


def test_aging_bounds_unpredicted_wait():
    """Fairness aging (bounded wait): a stream of predicted-short gangs
    beats a fresh unpredicted gang, but once the unpredicted gang has
    waited past aging_max_wait_s minus their remaining estimate, it
    ranks ahead — starvation is bounded, not open-ended."""
    client = FakeKubeClient()
    _seed(client, count=1)                  # one slice: strict ordering
    clock = FakeClock(step=0.0)             # advance manually
    q = make_queue(client, clock=clock, aging_max_wait_s=10.0)
    q.submit(_gang("d", "patient"))         # unpredicted: rank ~10
    q.predictor.observe("d", "quick1", steps_per_sec=1.0, last_step=998)
    q.submit(_gang("d", "quick1", total_steps=1000))   # remaining 2s
    q.schedule()
    assert q.state_of("d", "quick1") == PLACED   # short wins early
    assert q.state_of("d", "patient") == QUEUED
    q.release("d", "quick1")
    clock.t += 9.0                          # patient aged: rank ~1 < 2
    q.predictor.observe("d", "quick2", steps_per_sec=1.0, last_step=998)
    q.submit(_gang("d", "quick2", total_steps=1000))
    q.schedule()
    assert q.state_of("d", "patient") == PLACED  # bounded-wait kept
    assert q.state_of("d", "quick2") == QUEUED


def test_unpredicted_fifo_order_kept_under_aging():
    """Two unpredicted gangs age identically: FIFO order between them
    is preserved (the earlier submit has waited longer, ranks first)."""
    client = FakeKubeClient()
    _seed(client, count=1)
    q = make_queue(client)
    q.submit(_gang("d", "first"))
    q.submit(_gang("d", "second"))
    q.schedule()
    assert q.state_of("d", "first") == PLACED
    assert q.state_of("d", "second") == QUEUED


# -- queue: shrink offers to elastic gangs ------------------------------------


def test_shrink_offer_instead_of_preemption():
    """An elastic gang (min_slices floor) is OFFERED a shrink before
    anyone is evicted: the victim stays PLACED (the run keeps making
    progress), the CR carries the status.resize.offered nudge, and the
    preemptor's accelerator is reserved while the shrink settles."""
    client = FakeKubeClient()
    _seed(client, count=4)
    q = make_queue(client)
    client.create(tpujob("flex", "d", {
        "image": "x", "slices": 3, "hostsPerSlice": 2,
        "elastic": {"minSlices": 1, "maxSlices": 4}}))
    q.submit(_gang("d", "flex", slices=3, hosts=2, min_slices=1))
    q.schedule()
    assert q.state_of("d", "flex") == PLACED
    offers_before = DEFAULT_REGISTRY.counter(
        "kftpu_shrink_offers_total").get()
    q.submit(_gang("prod", "urgent", slices=2, hosts=2, priority=10))
    q.schedule()
    # offered, never Preempting — and the offer targets the LARGEST
    # feasible count, not the floor (ISSUE 12): urgent takes 2 of the
    # 4 slices, so flex keeps 2; the old floor-only behavior shrank it
    # to 1 and threw a slice away
    assert q.state_of("d", "flex") == PLACED
    assert q.shrink_requested("d", "flex") == 2
    assert DEFAULT_REGISTRY.counter(
        "kftpu_shrink_offers_total").get() == offers_before + 1
    job = client.get(API_VERSION, TPUJOB_KIND, "d", "flex")
    assert job["status"]["resize"]["offered"] == 2
    assert job["status"]["resize"]["by"] == "prod/urgent"
    # nobody backfills the accelerator while the shrink settles, and
    # the offer is not widened to a second victim
    q.submit(_gang("d", "tiny", slices=1))
    q.schedule()
    assert q.state_of("d", "tiny") == QUEUED
    assert q.shrink_requested("d", "tiny") is None
    # (retract the probe gang: with the larger offer the settled fleet
    # is capacity-exact — urgent 2 + flex 2 fill all 4 slices)
    q.release("d", "tiny")
    # the resize arrives (operator applied the spec edit): the offer
    # settles, the preemptor and the shrunk gang both place
    q.submit(_gang("d", "flex", slices=2, hosts=2, min_slices=1))
    q.schedule()
    assert q.shrink_requested("d", "flex") is None
    assert q.state_of("prod", "urgent") == PLACED
    assert q.state_of("d", "flex") == PLACED


def test_shrink_offer_revoked_when_preemptor_goes_away():
    """An offer whose beneficiary vanishes (released) or places
    elsewhere is WITHDRAWN: the victim's shrink_to clears and the CR
    nudge is erased — the elastic gang never pays a
    checkpoint-teardown-reshard for nobody."""
    client = FakeKubeClient()
    _seed(client, count=4)
    q = make_queue(client)
    client.create(tpujob("flex", "d", {
        "image": "x", "slices": 3, "hostsPerSlice": 2,
        "elastic": {"minSlices": 1, "maxSlices": 4}}))
    q.submit(_gang("d", "flex", slices=3, hosts=2, min_slices=1))
    q.schedule()
    q.submit(_gang("prod", "urgent", slices=2, hosts=2, priority=10))
    q.schedule()
    assert q.shrink_requested("d", "flex") == 2  # largest feasible
    # the preemptor is deleted before the operator applies the offer
    q.release("prod", "urgent")
    assert q.shrink_requested("d", "flex") is None
    job = client.get(API_VERSION, TPUJOB_KIND, "d", "flex")
    assert "offered" not in (job["status"].get("resize") or {})
    # and the next cycle does not re-offer (nothing is waiting)
    q.schedule()
    assert q.shrink_requested("d", "flex") is None

    # placed-elsewhere variant: capacity frees while the offer pends
    q.submit(_gang("prod", "urgent2", slices=2, hosts=2, priority=10))
    q.schedule()
    assert q.shrink_requested("d", "flex") == 2
    q.release("d", "flex")          # flex finishes on its own
    q.schedule()                    # urgent2 places on the freed slices
    assert q.state_of("prod", "urgent2") == PLACED


def test_shrink_offer_targets_largest_feasible_count():
    """ISSUE 12 satellite: the offer targets the LARGEST count in
    ``[minSlices, slices)`` the freed window accommodates — a 4-slice
    gang yielding to a 1-slice preemptor shrinks to 3, not to its
    floor of 1 (floor-only shrank 4→1 and idled two slices)."""
    client = FakeKubeClient()
    _seed(client, count=6)
    q = make_queue(client)
    client.create(tpujob("flex", "d", {
        "image": "x", "slices": 4, "hostsPerSlice": 2,
        "elastic": {"minSlices": 1, "maxSlices": 4}}))
    q.submit(_gang("d", "flex", slices=4, hosts=2, min_slices=1))
    q.submit(_gang("d", "filler", slices=2, hosts=2))
    q.schedule()
    assert q.state_of("d", "flex") == PLACED
    assert q.state_of("d", "filler") == PLACED     # all 6 slices busy
    q.submit(_gang("prod", "urgent", slices=1, hosts=2, priority=10))
    q.schedule()
    # urgent needs 1 of flex's 4 transiently-freed slices: 3 remain,
    # so the offer is 3 — the floor (1) would have been feasible too,
    # but strictly worse for the victim
    assert q.shrink_requested("d", "flex") == 3
    job = client.get(API_VERSION, TPUJOB_KIND, "d", "flex")
    assert job["status"]["resize"]["offered"] == 3
    # settle: both land, flex at 3 slices
    q.submit(_gang("d", "flex", slices=3, hosts=2, min_slices=1))
    q.schedule()
    assert q.state_of("prod", "urgent") == PLACED
    assert q.state_of("d", "flex") == PLACED


def test_shrink_infeasible_falls_back_to_eviction():
    """A floor that cannot free enough capacity is no offer at all —
    the queue falls back to the normal minimum-cost eviction."""
    client = FakeKubeClient()
    _seed(client, count=2)
    q = make_queue(client)
    client.create(tpujob("flex", "d", {"image": "x", "slices": 2,
                                       "hostsPerSlice": 2,
                                       "elastic": {"minSlices": 1,
                                                   "maxSlices": 2}}))
    q.submit(_gang("d", "flex", slices=2, hosts=2, min_slices=1))
    q.schedule()
    assert q.state_of("d", "flex") == PLACED
    # urgent needs BOTH slices: shrinking flex to 1 still blocks it
    q.submit(_gang("prod", "urgent", slices=2, hosts=2, priority=10))
    q.schedule()
    assert q.shrink_requested("d", "flex") is None
    assert q.state_of("d", "flex") == PREEMPTING


# -- queue: preemption -------------------------------------------------------


def _preemption_cluster():
    client = FakeKubeClient()
    _seed(client, count=4)
    clock = FakeClock()
    collector = SpanCollector()
    q = make_queue(client, clock=clock,
                   tracer=Tracer(collector, clock=clock),
                   checkpoint_step=lambda ns, name: {"low1": 50,
                                                     "low2": 90}.get(name))
    return client, q, collector


def test_preemption_picks_min_cost_victim():
    client, q, _ = _preemption_cluster()
    # equal chips; low2's checkpoint (step 90 of 100) loses least work
    q.predictor.observe("d", "low1", steps_per_sec=1.0, last_step=100)
    q.predictor.observe("d", "low2", steps_per_sec=1.0, last_step=100)
    for name in ("low1", "low2"):
        client.create(tpujob(name, "d", {"image": "x", "hostsPerSlice": 2}))
        q.submit(_gang("d", name))
    q.schedule()
    assert q.state_of("d", "low1") == PLACED
    assert q.state_of("d", "low2") == PLACED
    before = DEFAULT_REGISTRY.counter("kftpu_preemptions_total").get()
    q.submit(_gang("prod", "urgent", slices=3, priority=10))
    q.schedule()
    assert q.state_of("d", "low2") == PREEMPTING
    assert q.state_of("d", "low1") == PLACED
    assert q.preemption_requested("d", "low2")
    assert DEFAULT_REGISTRY.counter(
        "kftpu_preemptions_total").get() == before + 1
    # the signal landed on the victim's CR
    job = client.get(API_VERSION, TPUJOB_KIND, "d", "low2")
    assert job["status"]["preemption"]["requested"] is True
    assert job["status"]["preemption"]["by"] == "prod/urgent"
    # a second cycle must not widen the blast radius while it settles
    q.schedule()
    assert q.state_of("d", "low1") == PLACED


def test_nonpreemptible_and_equal_priority_are_safe():
    client, q, _ = _preemption_cluster()
    client.create(tpujob("low1", "d", {"image": "x", "hostsPerSlice": 2,
                                       "preemptible": False}))
    q.submit(_gang("d", "low1", slices=4, preemptible=False))
    q.schedule()
    q.submit(_gang("prod", "peer", slices=1, priority=0))     # same class
    q.submit(_gang("prod", "urgent", slices=1, priority=10))  # higher
    q.schedule()
    # nothing preemptible: both waits hold, nobody is evicted
    assert q.state_of("d", "low1") == PLACED
    assert q.state_of("prod", "peer") == QUEUED
    assert q.state_of("prod", "urgent") == QUEUED


def test_confirm_preempted_requeues_at_class_head():
    client, q, _ = _preemption_cluster()
    q.predictor.observe("d", "low1", steps_per_sec=1.0, last_step=100)
    q.predictor.observe("d", "low2", steps_per_sec=1.0, last_step=100)
    for name in ("low1", "low2"):
        client.create(tpujob(name, "d", {"image": "x", "hostsPerSlice": 2}))
        q.submit(_gang("d", name))
    q.schedule()
    q.submit(_gang("prod", "urgent", slices=3, priority=10))
    q.schedule()
    assert q.state_of("d", "low2") == PREEMPTING
    q.confirm_preempted("d", "low2", 90)
    assert q.state_of("d", "low2") == QUEUED
    assert q.last_checkpoint_step("d", "low2") == 90
    # ahead of every other class-0 gang, even a predicted-short one
    q.predictor.observe("d", "newcomer", steps_per_sec=100.0, last_step=999)
    q.submit(_gang("d", "newcomer", total_steps=1000))
    names = [g["name"] for g in q.status()["gangs"]]
    assert set(names) >= {"low1", "low2", "urgent", "newcomer"}
    # urgent places first (higher class) onto the freed capacity
    q.schedule()
    assert q.state_of("prod", "urgent") == PLACED
    assert q.state_of("d", "low2") == QUEUED  # waits for capacity again


def test_no_backfill_onto_a_preempting_gangs_accelerator():
    """The eviction must pay off: once a gang preempts for the next
    free window, lower-ordered gangs may not backfill onto the freed
    (or about-to-free) slices — that would waste the eviction and loop
    the queue into preempting forever."""
    client = FakeKubeClient()
    _seed(client, count=2)
    q = make_queue(client)
    client.create(tpujob("low1", "d", {"image": "x", "hostsPerSlice": 2}))
    q.predictor.observe("d", "low1", steps_per_sec=1.0, last_step=100)
    q.submit(_gang("d", "low1"))
    q.schedule()
    assert q.state_of("d", "low1") == PLACED        # 1 slice free
    q.submit(_gang("prod", "urgent", slices=2, priority=10))
    q.submit(_gang("d", "tiny", slices=1))
    q.schedule()
    assert q.state_of("d", "low1") == PREEMPTING
    # tiny would fit the free slice, but urgent paid for that window
    assert q.state_of("d", "tiny") == QUEUED
    q.confirm_preempted("d", "low1", 90)
    q.schedule()
    assert q.state_of("prod", "urgent") == PLACED
    assert q.state_of("d", "tiny") == QUEUED        # still no capacity


def test_unknown_progress_victim_never_reads_cheap():
    """A victim with no telemetry has unknowable lost work: it must
    sort as maximal cost, not zero — the observed victim with a fresh
    checkpoint is the honest minimum-cost choice."""
    client = FakeKubeClient()
    _seed(client, count=4)
    q = make_queue(client,
                   checkpoint_step=lambda ns, name: {"seen": 90}.get(name))
    q.predictor.observe("d", "seen", steps_per_sec=1.0, last_step=100)
    for name in ("seen", "silent"):
        client.create(tpujob(name, "d", {"image": "x", "hostsPerSlice": 2}))
        q.submit(_gang("d", name))
    q.schedule()
    q.submit(_gang("prod", "urgent", slices=3, priority=10))
    q.schedule()
    assert q.state_of("d", "seen") == PREEMPTING    # lost 10 steps
    assert q.state_of("d", "silent") == PLACED      # unknown ≠ cheap


# -- contention separation through the queue ---------------------------------


def test_contention_separates_concurrent_gangs():
    client = FakeKubeClient()
    # heterogeneous inventory: slice 0 = 4 hosts, 1-2 = 2 hosts,
    # 3-5 = 4 hosts (hosts == node count per slice index)
    for s, hosts in enumerate([4, 2, 2, 4, 4, 4]):
        for h in range(hosts):
            client.create({
                "apiVersion": "v1", "kind": "Node",
                "metadata": {"name": f"n-{s}-{h}",
                             "labels": {SHAPE_LABEL: "v5e-16",
                                        SLICE_INDEX_LABEL: str(s)}}})
    # slices 4,5 temporarily busy so gang A lands on the spread window
    # [0,3] (riding links 0..2), the shape real fragmentation produces
    pads = []
    for s in (4, 5):
        for h in range(4):
            name = f"pad-{s}-{h}"
            client.create({
                "apiVersion": "v1", "kind": "Pod",
                "metadata": {"name": name, "namespace": "pad",
                             "labels": {ASSIGNED_SLICE_LABEL:
                                        f"v5e-16_{s}"}},
                "status": {"phase": "Running"}})
            pads.append(name)
    q = make_queue(client)
    q.submit(_gang("d", "ring-a", slices=2, hosts=4, accelerator="v5e-16"))
    q.schedule()
    assert q.placement_for("d", "ring-a") == ["v5e-16_0", "v5e-16_3"]
    for name in pads:
        client.delete("v1", "Pod", "pad", name)
    # gang B (2 small slices): the tight window [1,2] sits INSIDE A's
    # span; an uncontended [4,5] exists and must win despite its waste
    q.submit(_gang("d", "ring-b", slices=2, hosts=2, accelerator="v5e-16"))
    q.schedule()
    assert q.placement_for("d", "ring-b") == ["v5e-16_4", "v5e-16_5"]
    # and the waste-first twin would have collided:
    assert choose_slices_py([4, 2, 2, 4, 4, 4], [0, 2, 2, 0, 4, 4],
                            2, 2) == [1, 2]


# -- operator integration ----------------------------------------------------


class CountingCheckpointer(PreemptionCheckpointer):
    """Counts saves; optionally writes through a real CheckpointManager
    so the resume half of the protocol is the production code path."""

    def __init__(self, steps=None, manager=None, state=None):
        self.steps = dict(steps or {})
        self.manager = manager
        self.state = state
        self.save_calls = []

    def save(self, job):
        ns = job["metadata"]["namespace"]
        name = job["metadata"]["name"]
        self.save_calls.append((ns, name))
        step = self.steps.get(name)
        if self.manager is not None and step is not None:
            self.manager.save(step, self.state, wait=True)
        return step

    def latest_step(self, ns, name):
        return self.steps.get(name)


def _operator_cluster(tmp_path=None, quota_chips=None):
    client = FakeKubeClient()
    _seed(client, count=4)
    if quota_chips is not None:
        _quota(client, "tenant", quota_chips)
    clock = FakeClock()
    collector = SpanCollector()
    tracer = Tracer(collector, clock=clock)
    ckpt = CountingCheckpointer(steps={"low1": 50, "low2": 90})
    q = GangQueue(client, clock=clock, tracer=tracer,
                  checkpoint_step=ckpt.latest_step)
    op = TpuJobOperator(client, clock=clock, tracer=tracer, queue=q,
                        checkpointer=ckpt)
    return client, q, op, ckpt, collector


def _pods(client, ns, job):
    return client.list("v1", "Pod", ns, label_selector={JOB_LABEL: job})


def _set_phase(client, ns, job, phase):
    for pod in _pods(client, ns, job):
        pod.setdefault("status", {})["phase"] = phase
        client.update_status(pod)


def test_operator_quota_blocked_job_holds_with_condition():
    client, q, op, _, _ = _operator_cluster(quota_chips=8)
    client.create(tpujob("a", "tenant", {"image": "x", "hostsPerSlice": 2}))
    client.create(tpujob("b", "tenant", {"image": "x", "hostsPerSlice": 2}))
    assert op.reconcile("tenant", "a") == 1.0
    assert len(_pods(client, "tenant", "a")) == 2
    assert op.reconcile("tenant", "b") == 5.0
    assert _pods(client, "tenant", "b") == []
    job = client.get(API_VERSION, TPUJOB_KIND, "tenant", "b")
    conds = {c["reason"] for c in job["status"]["conditions"]}
    assert "QuotaExceeded" in conds
    # tenant a finishing frees the quota; b admits and places
    _set_phase(client, "tenant", "a", "Succeeded")
    op.reconcile("tenant", "a")
    op.reconcile("tenant", "b")
    assert len(_pods(client, "tenant", "b")) == 2


def test_operator_capacity_starved_job_queues():
    client, q, op, _, _ = _operator_cluster()
    client.create(tpujob("big", "d", {"image": "x", "slices": 5,
                                      "hostsPerSlice": 2}))
    assert op.reconcile("d", "big") == 5.0
    job = client.get(API_VERSION, TPUJOB_KIND, "d", "big")
    assert job["status"]["phase"] == PHASE_PENDING
    assert any(c["reason"] == "AwaitingCapacity"
               for c in job["status"]["conditions"])


def test_preempt_requeue_resume_end_to_end(tmp_path):
    """The acceptance scenario (ISSUE 8): a saturating low-priority
    workload admits under quota, a high-priority gang preempts the
    minimum-cost victim (one checkpoint save, Preempted condition,
    head-of-queue requeue), the victim resumes once capacity frees with
    its step clock intact via CheckpointManager.restore_or_init, and
    one trace carries admit→predict→place→preempt→requeue while
    kftpu_queue_depth / kftpu_preemptions_total move accordingly."""
    from kubeflow_tpu.train.checkpoint import CheckpointManager

    client, q, op, ckpt, collector = _operator_cluster(quota_chips=16)
    state = {"w": np.arange(4.0), "step": np.asarray(90)}
    ckpt.manager = CheckpointManager(str(tmp_path / "low2"), keep=2)
    ckpt.state = state
    depth = DEFAULT_REGISTRY.gauge("kftpu_queue_depth")
    preemptions = DEFAULT_REGISTRY.counter("kftpu_preemptions_total")
    preempt_before = preemptions.get()

    # 1. the low-priority workload saturates its 16-chip quota
    for name in ("low1", "low2"):
        client.create(tpujob(name, "tenant", {
            "image": "x", "hostsPerSlice": 2, "totalSteps": 1000,
            "checkpointDir": str(tmp_path / name)}))
        op.reconcile("tenant", name)
        assert len(_pods(client, "tenant", name)) == 2
    client.create(tpujob("low3", "tenant", {"image": "x",
                                            "hostsPerSlice": 2}))
    op.reconcile("tenant", "low3")
    assert q.state_of("tenant", "low3") == BLOCKED  # quota admission
    assert depth.get(state=PLACED) == 2
    # telemetry feeds the predictor (the PR 5 loop closed)
    q.predictor.observe("tenant", "low1", steps_per_sec=1.0, last_step=100)
    q.predictor.observe("tenant", "low2", steps_per_sec=1.0, last_step=100)

    # 2. a high-priority gang arrives; 2 free slices < the 3 it needs
    client.create(tpujob("urgent", "prod", {
        "image": "x", "slices": 3, "hostsPerSlice": 2, "priority": 10}))
    op.reconcile("prod", "urgent")
    assert _pods(client, "prod", "urgent") == []
    # minimum-cost victim: equal chips, low2's checkpoint is freshest
    assert q.state_of("tenant", "low2") == PREEMPTING

    # 3. the victim checkpoints exactly once, tears down, requeues
    op.reconcile("tenant", "low2")
    assert ckpt.save_calls == [("tenant", "low2")]
    assert _pods(client, "tenant", "low2") == []
    job = client.get(API_VERSION, TPUJOB_KIND, "tenant", "low2")
    conds = {(c["type"], c["reason"])
             for c in job["status"]["conditions"]}
    assert ("Preempted", "RequeuedForPriority") in conds
    assert job["status"]["preemption"] == {
        "requested": False, "lastCheckpointStep": 90, "count": 1,
        "by": "prod/urgent"}
    assert q.state_of("tenant", "low2") == QUEUED
    assert preemptions.get() == preempt_before + 1

    # 4. the preemptor places on the freed capacity
    op.reconcile("prod", "urgent")
    assert len(_pods(client, "prod", "urgent")) == 6
    assert {p["metadata"]["labels"][ASSIGNED_SLICE_LABEL]
            for p in _pods(client, "prod", "urgent")} \
        .isdisjoint({p["metadata"]["labels"][ASSIGNED_SLICE_LABEL]
                     for p in _pods(client, "tenant", "low1")})
    op.reconcile("tenant", "low2")
    assert _pods(client, "tenant", "low2") == []  # still waiting

    # 5. the preemptor finishes; the victim resumes, step clock intact
    _set_phase(client, "prod", "urgent", "Succeeded")
    op.reconcile("prod", "urgent")
    op.reconcile("tenant", "low2")
    assert len(_pods(client, "tenant", "low2")) == 2
    restored, start_step = ckpt.manager.restore_or_init(state)
    assert start_step == 90                       # the production path
    np.testing.assert_array_equal(restored["w"], state["w"])
    assert q.last_checkpoint_step("tenant", "low2") == 90
    # low3 admits too now that low2's quota share briefly freed? no —
    # low2 is back; low3 stays blocked until a sibling truly finishes
    assert q.state_of("tenant", "low3") == BLOCKED
    _set_phase(client, "tenant", "low1", "Succeeded")
    op.reconcile("tenant", "low1")
    op.reconcile("tenant", "low3")
    assert len(_pods(client, "tenant", "low3")) == 2

    # 6. one trace tells the whole story: the preemptor's identity-
    # derived trace holds admit→predict→place→preempt→requeue
    uid = client.get(API_VERSION, TPUJOB_KIND, "prod",
                     "urgent")["metadata"]["uid"]
    trace_id, _ = tpujob_trace_ids("prod", "urgent", uid)
    names = [s.name for s in collector.spans() if s.trace_id == trace_id]
    for expected in ("scheduler.queue.admit", "scheduler.queue.predict",
                     "scheduler.queue.place", "scheduler.queue.preempt",
                     "scheduler.queue.requeue"):
        assert expected in names, (expected, names)
    order = [names.index(n) for n in
             ("scheduler.queue.admit", "scheduler.queue.preempt",
              "scheduler.queue.requeue", "scheduler.queue.place")]
    assert order == sorted(order)  # admit → preempt → requeue → place
    ckpt.manager.close()


def test_elastic_resize_reflows_through_queue():
    client, q, op, _, _ = _operator_cluster()
    client.create(tpujob("j", "d", {"image": "x", "slices": 1,
                                    "hostsPerSlice": 2}))
    op.reconcile("d", "j")
    _set_phase(client, "d", "j", "Running")
    op.reconcile("d", "j")
    job = client.get(API_VERSION, TPUJOB_KIND, "d", "j")
    job["spec"]["slices"] = 2
    client.update(job)
    op.reconcile("d", "j")          # detects stale shape, tears down
    op.reconcile("d", "j")          # re-places at the new shape
    assert len(_pods(client, "d", "j")) == 4
    assert len(q.placement_for("d", "j")) == 2


def test_lost_worker_recreated_on_granted_slices():
    client, q, op, _, _ = _operator_cluster()
    client.create(tpujob("j", "d", {"image": "x", "hostsPerSlice": 2}))
    op.reconcile("d", "j")
    granted = q.placement_for("d", "j")
    victim = _pods(client, "d", "j")[0]
    client.delete("v1", "Pod", "d", victim["metadata"]["name"])
    op.reconcile("d", "j")
    pods = _pods(client, "d", "j")
    assert len(pods) == 2
    assert all(p["metadata"]["labels"][ASSIGNED_SLICE_LABEL] == granted[0]
               for p in pods)


def test_stale_grant_is_invalidated_not_double_booked():
    client, q, op, _, _ = _operator_cluster()
    created = client.create(tpujob("j", "d",
                                   {"image": "x", "hostsPerSlice": 2}))
    q.submit(_gang("d", "j", uid=created["metadata"]["uid"]))
    q.schedule()
    granted = q.placement_for("d", "j")
    # an out-of-band actor claims the granted slice before pods exist
    client.create({
        "apiVersion": "v1", "kind": "Pod",
        "metadata": {"name": "squatter", "namespace": "x",
                     "labels": {ASSIGNED_SLICE_LABEL: granted[0]}},
        "status": {"phase": "Running"}})
    assert op.reconcile("d", "j") == 5.0
    assert _pods(client, "d", "j") == []
    job = client.get(API_VERSION, TPUJOB_KIND, "d", "j")
    assert any(c["reason"] == "PlacementStale"
               for c in job["status"]["conditions"])
    # next pass re-places on a different slice
    op.reconcile("d", "j")
    pods = _pods(client, "d", "j")
    assert pods and all(
        p["metadata"]["labels"][ASSIGNED_SLICE_LABEL] != granted[0]
        for p in pods)


# -- shared reconciler runtime ----------------------------------------------


def wait_until(fn, timeout=5.0, interval=0.02):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if fn():
            return True
        time.sleep(interval)
    return False


def test_autoscaler_tick_runs_on_shared_runtime():
    from kubeflow_tpu.autoscale.policy import AutoscalePolicy
    from kubeflow_tpu.autoscale.reconciler import Autoscaler, ReplicaDriver

    class NullDriver(ReplicaDriver):
        def create(self, model, slice_id):
            return object()

        def warmup(self, model, handle):
            pass

        def is_warm(self, model, handle):
            return True

        def destroy(self, model, handle):
            pass

    collector = SpanCollector()
    autoscaler = Autoscaler(AutoscalePolicy(), NullDriver())
    autoscaler.tracer = Tracer(collector, clock=autoscaler.clock)
    autoscaler.watch("m")
    ctrl = autoscaler.build_controller(interval_s=0.02)
    ctrl.start()
    try:
        assert wait_until(lambda: any(
            s.name == "controller.reconcile"
            and s.attrs.get("controller") == "autoscaler"
            for s in collector.spans()))
        # the tick actually reconciled the watched model
        assert wait_until(
            lambda: autoscaler.status()["models"]["m"]["desired"]
            is not None)
    finally:
        ctrl.stop()


def test_scheduler_queue_controller_cycles():
    client = FakeKubeClient()
    _seed(client, count=2)
    collector = SpanCollector()
    q = make_queue(client, tracer=Tracer(collector))
    q.submit(_gang("d", "j"))
    ctrl = q.build_controller(interval_s=0.02)
    ctrl.start()
    try:
        assert wait_until(lambda: q.state_of("d", "j") == PLACED)
        assert wait_until(lambda: any(
            s.name == "controller.reconcile"
            and s.attrs.get("controller") == "scheduler-queue"
            for s in collector.spans()))
    finally:
        ctrl.stop()


def test_watch_controllers_emit_uniform_reconcile_spans():
    client = FakeKubeClient()
    collector = SpanCollector()
    op = TpuJobOperator(client, tracer=Tracer(collector))
    ctrl = op.build_controller()
    ctrl.start()
    try:
        client.create(tpujob("job1", "default", {
            "image": "img", "slices": 1, "hostsPerSlice": 2}))
        assert wait_until(lambda: any(
            s.name == "controller.reconcile"
            and s.attrs.get("controller") == "tpujob-operator"
            and s.attrs.get("name") == "job1"
            for s in collector.spans()))
        reconciles = DEFAULT_REGISTRY.counter(
            "kftpu_controller_reconciles_total")
        assert reconciles.get(controller="tpujob-operator") >= 1
    finally:
        ctrl.stop()


def test_run_loop_rides_the_controller_runtime():
    from kubeflow_tpu.autoscale.policy import AutoscalePolicy
    from kubeflow_tpu.autoscale.reconciler import Autoscaler, ReplicaDriver
    from kubeflow_tpu.autoscale.service import run_loop

    ticks = []

    class Probe(Autoscaler):
        def reconcile_all(self, now=None):
            ticks.append(1)

    handle = run_loop(Probe(AutoscalePolicy(), ReplicaDriver()), 0.02)
    try:
        assert wait_until(lambda: len(ticks) >= 2)
    finally:
        handle.stop.set()
    n = len(ticks)
    assert not wait_until(lambda: len(ticks) > n + 1, timeout=0.3)


# -- dashboard surface -------------------------------------------------------


def test_dashboard_scheduler_route():
    from kubeflow_tpu.dashboard.server import DashboardApi

    client = FakeKubeClient()
    _seed(client, count=2)
    q = make_queue(client)
    q.submit(_gang("d", "j", priority=3))
    q.schedule()
    api = DashboardApi(client, scheduler_queue=q,
                       authorize=lambda *a: True)
    code, body = api.handle("GET", "/api/metrics/scheduler", None)
    assert code == 200
    assert body["depth"][PLACED] == 1
    gang = body["gangs"][0]
    assert (gang["name"], gang["priority"]) == ("j", 3)
    # no queue attached: registry series still answer
    bare = DashboardApi(client, authorize=lambda *a: True)
    code, body = bare.handle("GET", "/api/metrics/scheduler", None)
    assert code == 200 and "metrics" in body
