"""Serving extras + dashboard backend tests.

Reference surfaces: traffic-split Istio weighting
(``tf-serving-service-template.libsonnet``), http-proxy request bridge
(``components/k8s-model-server/http-proxy/server.py``), batch predict
(``kubeflow/tf-batch-predict``), dashboard REST (``app/api.ts:78-150``).
"""

import io
import json

import jax
import numpy as np
import pytest

from kubeflow_tpu.config.deployment import ComponentSpec, DeploymentConfig
from kubeflow_tpu.dashboard import DashboardApi
from kubeflow_tpu.k8s import FakeKubeClient
from kubeflow_tpu.manifests.registry import render_component
from kubeflow_tpu.serving import (
    ModelServer,
    PredictProxy,
    batch_predict_job,
    export_model,
    run_batch_predict,
)
from kubeflow_tpu.tenancy import profile


@pytest.fixture(scope="module")
def mnist_repo(tmp_path_factory):
    from kubeflow_tpu.models import MnistCnn

    repo = tmp_path_factory.mktemp("models")
    model = MnistCnn()
    x = np.zeros((1, 28, 28, 1), np.float32)
    params = jax.jit(model.init)(jax.random.key(0), x)["params"]
    export_model(str(repo / "mnist"), "mnist", params, version=1)
    return repo


# -- traffic split ---------------------------------------------------------

def test_serving_traffic_split_manifests():
    config = DeploymentConfig(name="demo")
    objs = render_component(config, ComponentSpec(
        "serving", params={"traffic_split": {"v1": 90, "v2": 10}}))
    kinds = [(x["kind"], x["metadata"]["name"]) for x in objs]
    assert ("Deployment", "model-server-v1") in kinds
    assert ("Deployment", "model-server-v2") in kinds
    vs = [x for x in objs if x["kind"] == "VirtualService"][0]
    # one weighted route per port: REST and gRPC keep their own ports
    assert [r["match"][0]["port"] for r in vs["spec"]["http"]] == [8500, 9000]
    for http_route in vs["spec"]["http"]:
        port = http_route["match"][0]["port"]
        routes = http_route["route"]
        assert [(r["destination"]["subset"], r["weight"])
                for r in routes] == [("v1", 90), ("v2", 10)]
        assert all(r["destination"]["port"]["number"] == port
                   for r in routes)
    dr = [x for x in objs if x["kind"] == "DestinationRule"][0]
    assert [s["name"] for s in dr["spec"]["subsets"]] == ["v1", "v2"]


def test_serving_traffic_split_must_sum_100():
    config = DeploymentConfig(name="demo")
    with pytest.raises(ValueError, match="sum to 100"):
        render_component(config, ComponentSpec(
            "serving", params={"traffic_split": {"v1": 50, "v2": 20}}))
    with pytest.raises(ValueError, match=r"in \[0,100\]"):
        render_component(config, ComponentSpec(
            "serving", params={"traffic_split": {"v1": 150, "v2": -50}}))


def test_serving_proxy_manifests():
    config = DeploymentConfig(name="demo")
    objs = render_component(config, ComponentSpec("serving",
                                                  params={"proxy": True}))
    kinds = [(x["kind"], x["metadata"]["name"]) for x in objs]
    assert ("Deployment", "model-server-proxy") in kinds
    assert ("Service", "model-server-proxy") in kinds


# -- http proxy ------------------------------------------------------------

def test_proxy_forwards_and_logs(mnist_repo):
    server = ModelServer(str(mnist_repo), port=0)
    port = server.start()
    logbuf = io.StringIO()
    proxy = PredictProxy(f"http://127.0.0.1:{port}", log_stream=logbuf)
    body = {"instances": np.zeros((2, 28, 28, 1)).tolist()}
    code, payload = proxy.handle("POST", "/model/mnist:predict", body,
                                 user="alice")
    assert code == 200, payload
    assert len(payload["predictions"]) == 2
    record = json.loads(logbuf.getvalue().splitlines()[0])
    assert record["model"] == "mnist"
    assert record["status"] == 200
    assert record["instances"] == 2
    assert record["user"] == "alice"
    assert record["latency_ms"] > 0
    server.stop()


def test_proxy_backend_down_is_502():
    proxy = PredictProxy("http://127.0.0.1:1", log_stream=io.StringIO())
    code, payload = proxy.handle("POST", "/model/m:predict",
                                 {"instances": [[1]]})
    assert code == 502
    assert "unreachable" in payload["error"]


def test_proxy_health_and_404():
    proxy = PredictProxy("http://b", log_stream=io.StringIO())
    assert proxy.handle("GET", "/healthz", None)[0] == 200
    assert proxy.handle("GET", "/model/m:predict", None)[0] == 404


# -- batch predict ---------------------------------------------------------

def test_batch_predict_end_to_end(mnist_repo, tmp_path):
    inp = tmp_path / "in.jsonl"
    with open(inp, "w") as f:
        for _ in range(7):  # deliberately not a multiple of batch size
            f.write(json.dumps(np.zeros((28, 28, 1)).tolist()) + "\n")
    out = tmp_path / "out.jsonl"
    summary = run_batch_predict(str(mnist_repo / "mnist"), str(inp),
                                str(out), batch_size=4)
    assert summary["instances"] == 7
    assert summary["model_version"] == 1
    preds = [json.loads(line) for line in open(out)]
    assert len(preds) == 7
    assert len(preds[0]["prediction"]) == 10  # mnist logits


def test_batch_predict_missing_model(tmp_path):
    with pytest.raises(FileNotFoundError):
        run_batch_predict(str(tmp_path / "nope"), "in", "out")


def test_batch_predict_job_manifest():
    job = batch_predict_job(
        "bp", "kubeflow", model_base_path="/models/m",
        input_path="/data/in.jsonl", output_path="/data/out.jsonl",
        tpu_chips=4)
    assert job["kind"] == "Job"
    ctr = job["spec"]["template"]["spec"]["containers"][0]
    assert "--model-base-path" in ctr["args"]
    assert ctr["resources"]["limits"]["google.com/tpu"] == 4
    assert job["spec"]["template"]["spec"]["restartPolicy"] == "OnFailure"


# -- dashboard -------------------------------------------------------------

@pytest.fixture
def dash_client():
    client = FakeKubeClient()
    from kubeflow_tpu.k8s import objects as o

    client.create(o.namespace("alice"))
    ns = client.get("v1", "Namespace", "", "alice")
    ns["metadata"]["annotations"] = {"owner": "alice@x.com"}
    client.update(ns)
    client.create(profile("alice", "alice@x.com"))
    client.create({
        "apiVersion": "v1", "kind": "Event",
        "metadata": {"name": "e1", "namespace": "alice"},
        "lastTimestamp": "2026-07-29T10:00:00Z", "type": "Normal",
        "reason": "Created", "message": "job created",
        "involvedObject": {"name": "train"},
    })
    return client


def test_dashboard_env_info_and_namespaces(dash_client):
    api = DashboardApi(dash_client, platform="gcp-tpu")
    code, info = api.handle("GET", "/api/env-info", None, user="alice@x.com")
    assert code == 200
    assert info["user"] == "alice@x.com"
    assert "alice" in info["namespaces"]
    assert info["platform"]["kind"] == "gcp-tpu"
    code, nss = api.handle("GET", "/api/namespaces", None)
    assert {"name": "alice", "owner": "alice@x.com"} in nss


def test_dashboard_activities(dash_client):
    api = DashboardApi(dash_client)
    code, acts = api.handle("GET", "/api/activities/alice", None,
                            user="alice@x.com")
    assert code == 200
    assert acts[0]["reason"] == "Created"
    assert acts[0]["object"] == "train"
    # events carry workload names/failure text: cross-tenant reads denied
    assert api.handle("GET", "/api/activities/alice", None,
                      user="mallory")[0] == 403


def test_dashboard_workgroup(dash_client):
    api = DashboardApi(dash_client)
    _, wg = api.handle("GET", "/api/workgroup/exists", None,
                       user="alice@x.com")
    assert wg == {"hasWorkgroup": True, "workgroups": ["alice"]}
    _, wg = api.handle("GET", "/api/workgroup/exists", None, user="bob@x.com")
    assert wg["hasWorkgroup"] is False


def test_dashboard_metrics_and_links(dash_client):
    api = DashboardApi(dash_client)
    code, metrics = api.handle("GET", "/api/metrics/kftpu_", None)
    assert code == 200 and isinstance(metrics, list)
    _, links = api.handle("GET", "/api/dashboard-links", None)
    assert any(card["text"] == "TPU Jobs" for card in links)
    assert any(card["link"] == "/studies.html" for card in links)
    assert any(card["link"] == "/runs.html" for card in links)
    assert api.handle("POST", "/api/env-info", {})[0] == 405


def test_dashboard_cluster_metrics_scrapes_targets(dash_client):
    """Weak-8 fix: the metrics panel aggregates component serve_metrics
    endpoints, not the dashboard's own process registry."""
    from kubeflow_tpu.dashboard.server import ClusterMetricsService
    from kubeflow_tpu.utils.metrics import Registry, serve_metrics

    reg = Registry()
    reg.counter("kftpu_test_jobs_total", "jobs").inc()
    t = serve_metrics(0, reg)
    try:
        port = t.server.server_address[1]
        svc = ClusterMetricsService(
            {"operator": f"http://127.0.0.1:{port}/metrics",
             "down": "http://127.0.0.1:9/metrics"})
        out = svc.query("kftpu_")
        by_metric = {m["metric"]: m["value"] for m in out}
        assert by_metric['up{target="operator"}'] == 1.0
        assert by_metric['up{target="down"}'] == 0.0
        assert any("kftpu_test_jobs_total" in k and v == 1.0
                   for k, v in by_metric.items())
    finally:
        t.server.shutdown()


def test_dashboard_studies_pages(dash_client):
    from kubeflow_tpu.tuning.study import STUDY_LABEL, study, trial

    s = study("opt-lr", "alice", {
        "algorithm": {"name": "bayesian"},
        "objective": {"metric": "loss", "type": "minimize"},
        "parameters": [{"name": "lr", "type": "double", "min": 1e-4,
                        "max": 1e-1}],
        "trialTemplate": {"image": "img"},
    })
    dash_client.create(s)
    s = dash_client.get(s["apiVersion"], s["kind"], "alice", "opt-lr")
    s["status"] = {"phase": "Running", "trials": 2, "trialsRunning": 1,
                   "bestTrial": {"name": "opt-lr-0", "objective": 0.4}}
    dash_client.update_status(s)
    t0 = trial(s, 0, {"lr": 0.01})
    t0["status"] = {"phase": "Succeeded", "observation": {"loss": 0.4}}
    t1 = trial(s, 1, {"lr": 0.05})
    t1["status"] = {"phase": "Running"}
    dash_client.create(t0)
    dash_client.create(t1)

    api = DashboardApi(dash_client)
    u = "alice@x.com"  # owns profile "alice" (Profile-RBAC default authz)
    code, studies = api.handle("GET", "/api/studies/alice", None, user=u)
    assert code == 200
    assert studies[0]["name"] == "opt-lr"
    assert studies[0]["bestTrial"]["objective"] == 0.4

    code, detail = api.handle("GET", "/api/studies/alice/opt-lr", None,
                              user=u)
    assert code == 200
    objs = {t["name"]: t["objective"] for t in detail["trials"]}
    assert objs[t0["metadata"]["name"]] == 0.4
    assert objs[t1["metadata"]["name"]] is None
    assert api.handle("GET", "/api/studies/alice/nope", None,
                      user=u)[0] == 404
    # cross-tenant reads are denied by default (no profile/binding)
    assert api.handle("GET", "/api/studies/alice", None,
                      user="mallory")[0] == 403


def test_dashboard_tpujobs_pages(dash_client):
    from kubeflow_tpu.operators.tpujob import TpuJobOperator, tpujob

    dash_client.create(tpujob("train", "alice", {
        "image": "img", "slices": 2, "hostsPerSlice": 2,
        "accelerator": "v5e-8"}))
    TpuJobOperator(dash_client).reconcile("alice", "train")

    api = DashboardApi(dash_client)
    u = "alice@x.com"
    code, jobs = api.handle("GET", "/api/tpujobs/alice", None, user=u)
    assert code == 200
    assert jobs[0]["name"] == "train"
    assert jobs[0]["slices"] == 2 and jobs[0]["workersTotal"] == 4

    code, detail = api.handle("GET", "/api/tpujobs/alice/train", None,
                              user=u)
    assert code == 200
    assert len(detail["workers"]) == 4
    slices = {w["slice"] for w in detail["workers"]}
    assert slices == {"0", "1"}
    assert api.handle("GET", "/api/tpujobs/alice/nope", None,
                      user=u)[0] == 404
    assert api.handle("GET", "/api/tpujobs/alice", None,
                      user="mallory")[0] == 403


def test_dashboard_runs_merges_live_and_archive(dash_client, tmp_path):
    from kubeflow_tpu.workflows import RunArchive, WorkflowController
    from kubeflow_tpu.workflows.workflow import (
        WORKFLOW_API_VERSION,
        container_step,
        workflow,
    )

    archive = RunArchive(str(tmp_path / "runs"))
    ctrl = WorkflowController(dash_client, archive=archive)
    dash_client.create(workflow("old-run", "alice",
                                [container_step("a", "img")]))
    ctrl.reconcile("alice", "old-run")
    for pod in dash_client.list("v1", "Pod", "alice"):
        pod.setdefault("status", {})["phase"] = "Succeeded"
        dash_client.update_status(pod)
    ctrl.reconcile("alice", "old-run")
    dash_client.delete(WORKFLOW_API_VERSION, "Workflow", "alice", "old-run")
    dash_client.create(workflow("live-run", "alice",
                                [container_step("b", "img")]))
    ctrl.reconcile("alice", "live-run")

    api = DashboardApi(dash_client, run_archive=archive)
    u = "alice@x.com"
    code, runs = api.handle("GET", "/api/runs/alice", None, user=u)
    assert code == 200
    by_name = {r["name"]: r for r in runs}
    assert by_name["old-run"]["live"] is False
    assert by_name["old-run"]["phase"] == "Succeeded"
    assert by_name["live-run"]["live"] is True

    code, detail = api.handle("GET", "/api/runs/alice/old-run", None, user=u)
    assert code == 200 and detail["live"] is False
    assert detail["status"]["nodes"]["a"]["phase"] == "Succeeded"
    code, detail = api.handle("GET", "/api/runs/alice/live-run", None,
                              user=u)
    assert code == 200 and detail["live"] is True
    assert api.handle("GET", "/api/runs/alice/nope", None, user=u)[0] == 404
    # a workflow spec (commands/env) must not leak across tenants
    assert api.handle("GET", "/api/runs/alice/live-run", None,
                      user="mallory")[0] == 403
