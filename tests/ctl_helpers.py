"""Shared helper for tests that drive the ctl CLI as a subprocess."""

import os
import subprocess
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run_ctl(*argv, cwd):
    return subprocess.run(
        [sys.executable, "-m", "kubeflow_tpu.cli", *argv],
        capture_output=True, text=True, cwd=cwd,
        env={**os.environ,
             "PYTHONPATH": REPO_ROOT + os.pathsep
             + os.environ.get("PYTHONPATH", "")})
