#!/usr/bin/env python
"""Elastic-training smoke gate (scripts/preflight.sh stage).

Drives the checkpoint-reshard-resume plane (docs/ELASTIC.md) end to end
on the CPU tier: a fake 4-slice gang (8 virtual devices, 2 per slice)
trains a tiny LM to step 50, catches a shrink signal, snapshots exactly
once, reshards onto 2 slices (4 devices), resumes at step 51, and
trains to step 100 — and the whole loss stream must match a
never-resized oracle run (same data stream, 4 slices throughout) after
the resync step, step for step. Also asserts the resize's
``elastic.snapshot → elastic.reshard → elastic.resume`` spans landed in
the job's identity-derived trace, in order. Exits nonzero on any
violated invariant.
"""

import sys
import tempfile

import numpy as np

sys.path.insert(0, ".")

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

SHRINK_AT = 50
TOTAL = 100
DEVICES_PER_SLICE = 2


def check(ok, what):
    if not ok:
        print(f"elastic smoke: FAIL — {what}", file=sys.stderr)
        sys.exit(1)
    print(f"  ok: {what}")


def build(tmp, collector, signal):
    from kubeflow_tpu.elastic import ElasticCoordinator, mesh_for_slices
    from kubeflow_tpu.models import Transformer, TransformerConfig
    from kubeflow_tpu.obs.trace import Tracer
    from kubeflow_tpu.train import TrainState, make_lm_train_step, \
        make_optimizer
    from kubeflow_tpu.train.checkpoint import CheckpointManager

    config = TransformerConfig(
        vocab_size=64, d_model=32, n_layers=2, n_heads=4, n_kv_heads=4,
        d_ff=64, max_seq_len=16, dtype=jnp.float32, remat=False)
    model = Transformer(config)
    tx = make_optimizer(1e-3, warmup_steps=2, decay_steps=TOTAL + 1)
    sample = jnp.zeros((8, 8), jnp.int32)

    def init_fn(rng):
        params = model.init(rng, sample)["params"]
        return TrainState.create(apply_fn=model.apply, params=params,
                                 tx=tx)

    def mesh_factory(n):
        return mesh_for_slices(
            n, devices=jax.devices()[:n * DEVICES_PER_SLICE])

    return ElasticCoordinator(
        manager=CheckpointManager(tmp), init_fn=init_fn,
        make_step=lambda m: make_lm_train_step(m),
        mesh_factory=mesh_factory, signal=signal,
        tracer=Tracer(collector), reinit=lambda n: None,
        job="smoke", namespace="default", uid="u")


def data_fn(step):
    rng = jax.random.fold_in(jax.random.key(1234), step)
    return (jax.random.randint(rng, (8, 8), 0, 64),)


def main():
    from kubeflow_tpu.elastic import ResizeSignal
    from kubeflow_tpu.obs.steps import tpujob_trace_ids
    from kubeflow_tpu.obs.trace import SpanCollector

    check(jax.device_count() >= 8,
          f"8 virtual devices available (have {jax.device_count()})")

    # -- elastic run: 4 slices to step 50, shrink signal, 2 slices on --
    collector = SpanCollector()
    signal = ResizeSignal()
    losses = {}
    coord = build(tempfile.mkdtemp(), collector, signal)

    def on_metrics(step, metrics):
        losses[step] = float(metrics["loss"])
        if step == SHRINK_AT:
            signal.request(2)

    coord.run(total_steps=TOTAL, n_slices=4, data_fn=data_fn,
              on_metrics=on_metrics)
    check(coord.n_slices == 2, "run finished on 2 slices")
    check(coord.resizes == 1, "exactly one resize")
    check(coord.snapshotter.saves == 1, "exactly one snapshot save")
    check(len(losses) == TOTAL, f"all {TOTAL} steps ran")

    # -- spans: snapshot -> reshard -> resume in the job's trace --------
    trace_id, _ = tpujob_trace_ids("default", "smoke", "u")
    names = [s.name for s in collector.spans()
             if s.trace_id == trace_id]
    check(names == ["elastic.snapshot", "elastic.reshard",
                    "elastic.resume"],
          f"resize spans in order in one trace ({names})")

    # -- the oracle: never resized, 4 slices throughout -----------------
    oracle = build(tempfile.mkdtemp(), SpanCollector(), ResizeSignal())
    oracle_losses = {}
    oracle.run(total_steps=TOTAL, n_slices=4, data_fn=data_fn,
               on_metrics=lambda s, m: oracle_losses.__setitem__(
                   s, float(m["loss"])))

    pre = all(losses[s] == oracle_losses[s]
              for s in range(1, SHRINK_AT + 1))
    check(pre, "pre-resize losses bit-identical to the oracle")
    post = [s for s in range(SHRINK_AT + 1, TOTAL + 1)
            if not np.isclose(losses[s], oracle_losses[s], rtol=1e-4,
                              atol=1e-6)]
    check(not post,
          f"post-resync loss stream matches the oracle (diverged at "
          f"{post[:5]})" if post else
          "post-resync loss stream matches the oracle")
    print("elastic smoke: ok")


if __name__ == "__main__":
    main()
