#!/usr/bin/env python
"""Compile/HBM profile smoke gate (scripts/preflight.sh stage 12).

Two planes, both on the CPU tier (docs/OBSERVABILITY.md "Compile &
memory"):

Real-jax plane — a live ``jax.jit`` compile must land in the
:class:`~kubeflow_tpu.obs.xprof.CompileLedger` through the
``jax.monitoring`` subscription, exactly once per compilation (jax
emits three duration events per compile; the jaxpr-trace and
MLIR-lowering ones must not count); ``timed_compile`` must fingerprint
the HLO and record a ``memory_analysis`` budget beside it; and the
:class:`~kubeflow_tpu.obs.xprof.HbmSampler` must degrade silently on
CPU (``memory_stats() is None``).

Fake-clock operator plane — injected compile events with job identity
become the goodput ledger's ground truth: ``startup_compile`` matches
the event-sourced seconds exactly (no beacon inference), the
histogram reads back through the tsdb and ``GET /api/metrics/query``,
``GET /api/jobs/<ns>/<name>/profile`` serves the compile summary +
budgets + beacon watermark, and an injected HBM climb walks the
``hbm-headroom`` rule ``Pending -> Firing -> Resolved`` with exactly
one k8s Event per transition.

Exits nonzero on any violated invariant.
"""

import math
import sys

sys.path.insert(0, ".")

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from kubeflow_tpu.dashboard.server import DashboardApi  # noqa: E402
from kubeflow_tpu.k8s import FakeKubeClient  # noqa: E402
from kubeflow_tpu.obs import xprof  # noqa: E402
from kubeflow_tpu.obs.alerts import (  # noqa: E402
    FIRING,
    INACTIVE,
    PENDING,
    RESOLVED,
    AlertManager,
    default_rules,
)
from kubeflow_tpu.obs.steps import publish_beacon  # noqa: E402
from kubeflow_tpu.obs.trace import SpanCollector, Tracer  # noqa: E402
from kubeflow_tpu.obs.tsdb import TimeSeriesStore  # noqa: E402
from kubeflow_tpu.obs.xprof import CompileLedger, HbmSampler  # noqa: E402
from kubeflow_tpu.operators.tpujob import (  # noqa: E402
    JOB_LABEL,
    PreemptionCheckpointer,
    TpuJobOperator,
    tpujob,
)
from kubeflow_tpu.manifests.components.tpujob_operator import (  # noqa: E402
    API_VERSION,
    TPUJOB_KIND,
)
from kubeflow_tpu.platform.local import fake_slice_nodes  # noqa: E402
from kubeflow_tpu.scheduler.queue import GangQueue  # noqa: E402
from kubeflow_tpu.utils import DEFAULT_REGISTRY  # noqa: E402


class Clock:
    def __init__(self, now=1000.0):
        self.now = now

    def __call__(self):
        return self.now


class NoDiskCkpt(PreemptionCheckpointer):
    def save(self, job):
        return None

    def latest_step(self, ns, name):
        return None


def check(ok, what):
    if not ok:
        print(f"FAIL: {what}")
        sys.exit(1)
    print(f"ok: {what}")


def real_jax_plane():
    """Live compiles on the CPU backend: monitoring subscription,
    one-event-per-compile filter, AOT fingerprint + budget, silent
    HBM degrade. Returns the recorded fingerprint."""
    ledger = CompileLedger(namespace="smoke", job="lab", worker=0)
    check(ledger.install() is True, "monitoring listener registered")
    check(ledger.install() is False,
          "second install is a no-op (no double subscription)")

    x = jnp.arange(8, dtype=jnp.float32)  # eager compile BEFORE count
    before = len(ledger.events)

    def fresh(v):
        return (v * 2.0 + 1.0).sum()

    jax.jit(fresh)(x).block_until_ready()
    got = len(ledger.events) - before
    check(got == 1,
          f"one jit compile -> exactly one ledger event (got {got}; "
          "jaxpr/MLIR duration events filtered out)")
    ev = ledger.events[-1]
    check(ev.seconds >= 0.0 and ev.generation == "cpu",
          "event carries wall seconds + backend generation")

    check(ledger.uninstall() is True, "explicit teardown unregisters")
    check(ledger.uninstall() is False, "second uninstall is a no-op")
    before = len(ledger.events)

    def after_teardown(v):
        return (v - 3.0) * v

    jax.jit(after_teardown)(x).block_until_ready()
    check(len(ledger.events) == before,
          "no events recorded after uninstall")

    # AOT wrapper fallback: fingerprint + memory_analysis budget
    y = jnp.ones((8, 8), dtype=jnp.float32)

    def mat(v):
        return v @ v

    compiled = ledger.timed_compile(jax.jit(mat), y, module="mat")
    ev = ledger.events[-1]
    check(ev.module == "mat" and ev.shape_class == "seq128_float32"
          and len(ev.fingerprint) == 16,
          "timed_compile records module/shape-class/fingerprint")
    budget = xprof.budget_for(ev.fingerprint)
    check(budget is not None
          and budget["bytes"].get("argument", 0) >= y.nbytes,
          "memory_analysis budget recorded beside the fingerprint")
    z = compiled(y)
    check(z.shape == (8, 8), "timed_compile returns the executable")

    # CPU silent degrade: real memory_stats() is None
    s = HbmSampler(namespace="smoke", job="lab", worker=0)
    check(s.sample() is None and s.beacon_fields() == {},
          "CPU memory_stats() is None -> sampler degrades silently")
    return ev.fingerprint


def main():
    fingerprint = real_jax_plane()
    xprof._reset_job_totals()  # isolate the operator plane

    ns = "smoke"
    client = FakeKubeClient()
    for node in fake_slice_nodes("v5e-8", count=1):
        client.create(node)
    clock = Clock()
    collector = SpanCollector()
    tracer = Tracer(collector, clock=clock)
    q = GangQueue(client, clock=clock, tracer=tracer,
                  checkpoint_step=lambda ns, name: None)
    op = TpuJobOperator(client, clock=clock, tracer=tracer, queue=q,
                        checkpointer=NoDiskCkpt())
    store = TimeSeriesStore(clock=clock)
    rule = next(r for r in default_rules() if r.name == "hbm-headroom")
    mgr = AlertManager(store, [rule], client=client, namespace=ns,
                       clock=clock, tracer=tracer)
    transitions = []

    def tick(dt=10.0):
        clock.now += dt
        op.reconcile(ns, "train")
        store.sample_registry(DEFAULT_REGISTRY)
        for st in mgr.evaluate():
            transitions.append((st.rule.name, st.state))

    client.create(tpujob("train", ns, {
        "image": "x", "slices": 1, "hostsPerSlice": 1}))
    op.reconcile(ns, "train")
    uid = client.get(API_VERSION, TPUJOB_KIND, ns,
                     "train")["metadata"]["uid"]
    pods = client.list("v1", "Pod", ns,
                       label_selector={JOB_LABEL: "train"})
    check(len(pods) == 1, "gang placed")
    for pod in pods:
        pod.setdefault("status", {})["phase"] = "Running"
        client.update_status(pod)
    # the worker's ledger boots WITH the gang: constructing it
    # announces the ground-truth source, so beacon inference never
    # attributes a compile second on this job
    led = CompileLedger(namespace=ns, job="train", uid=uid, worker=0,
                        clock=clock, tracer=tracer)
    tick()  # first fold: measured source present, zero seconds so far

    led.record("train_step", 4.5, shape_class="seq128_float32")
    led.record("train_step", 3.0, shape_class="seq128_float32")
    tick(dt=60.0)  # the fold carves exactly the event-sourced seconds

    g = client.get(API_VERSION, TPUJOB_KIND, ns,
                   "train")["status"]["goodput"]
    check(math.isclose(g["seconds"].get("startup_compile", 0.0), 7.5,
                       abs_tol=1e-9),
          "startup_compile == event-sourced compile seconds, exactly")
    check(g["seconds"].get("recompile", 0.0) == 0.0,
          "no recompile attributed before the first step")
    check(g["seconds"].get("unattributed", 0.0) > 0.0,
          "measured source -> beacon inference stood down "
          "(rest of the window is unattributed, not startup_compile)")

    # the histogram reads back through the tsdb + query API
    api = DashboardApi(client, authorize=lambda *a: True, tsdb=store,
                       collector=collector)
    code, body = api.handle(
        "GET",
        "/api/metrics/query?metric=kftpu_compile_seconds_sum"
        f"&label=namespace:{ns}&label=job:train", None)
    check(code == 200 and body["result"]
          and math.isclose(sum(r["value"] for r in body["result"]),
                           7.5, abs_tol=1e-9),
          "kftpu_compile_seconds reads back through /api/metrics/query")

    # beacon carries the watermark the profile route serves
    mem = {"bytes_in_use": 10 << 30, "peak_bytes_in_use": 11 << 30,
           "bytes_limit": 16 << 30}
    sampler = HbmSampler(namespace=ns, job="train", worker=0,
                         source=lambda: dict(mem))
    check(sampler.sample() is not None, "injected source samples")
    publish_beacon(client, ns, "train", 0,
                   {"step": 0, "hbm": sampler.beacon_fields()},
                   job_uid=uid)

    code, prof = api.handle("GET", f"/api/jobs/{ns}/train/profile",
                            None)
    check(code == 200 and prof["compile"]["count"] == 2
          and math.isclose(prof["compile"]["seconds"], 7.5,
                           abs_tol=1e-6),
          "profile route serves the event-sourced compile summary")
    check(math.isclose(prof["goodput"]["startupCompileSeconds"], 7.5,
                       abs_tol=1e-6),
          "profile route mirrors the ledger's measured compile state")
    check(fingerprint in prof["budgets"],
          "profile route serves the memory_analysis budgets")
    check(prof["hbm"]["inUseBytes"] == 10 << 30
          and prof["hbm"]["limitBytes"] == 16 << 30,
          "profile route serves the beacon HBM watermark")

    code, tel = api.handle("GET", f"/api/jobs/{ns}/train/telemetry",
                           None)
    check(code == 200 and tel["compile"]["count"] == 2
          and "hbm" in tel,
          "/telemetry gained the compile + hbm summaries")

    # injected HBM climb: hbm-headroom walks the FSM
    for _ in range(3):
        sampler.sample()
        tick()
    check(mgr._states["hbm-headroom"].state == INACTIVE,
          "rule inactive at 62% utilization")
    mem["bytes_in_use"] = int(15.2 * (1 << 30))  # 95% of limit
    for _ in range(15):
        sampler.sample()
        tick()
    mem["bytes_in_use"] = 8 << 30  # back to 50%
    for _ in range(15):
        sampler.sample()
        tick()
    names = [s for (r, s) in transitions if r == "hbm-headroom"]
    check(names == [PENDING, FIRING, RESOLVED],
          "hbm-headroom walked exactly Pending -> Firing -> Resolved")
    events = [e for e in client.list("v1", "Event", ns)
              if e["reason"].startswith("Alert")]
    check(sorted(e["reason"] for e in events)
          == ["AlertFiring", "AlertPending", "AlertResolved"],
          "exactly one Event per transition")
    check(sampler.peak_seen >= int(15.2 * (1 << 30)),
          "peak watermark is max-seen across samples")

    # the measured startup_compile never drifted during the climb
    g = client.get(API_VERSION, TPUJOB_KIND, ns,
                   "train")["status"]["goodput"]
    check(math.isclose(g["seconds"]["startup_compile"], 7.5,
                       abs_tol=1e-9),
          "compile attribution stable across later windows")

    print("profile smoke: PASS")


if __name__ == "__main__":
    main()
