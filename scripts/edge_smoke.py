#!/usr/bin/env python
"""Fleet-serving-edge smoke gate (docs/EDGE.md, preflight stage 9).

End to end on a fake 3-replica fleet, fully deterministic, no device:

1. prefix-affinity routing concentrates a warmed prefix: after a warm
   pass, the warm replica's trie hit-rate strictly beats every cold
   replica's on the same interleaved stream;
2. an overload burst at 2x the fleet's admission capacity sheds
   lowest-SLO-class-first, and ONE trace (the burst's root span) shows
   the shed/served split — the ROADMAP acceptance artifact, written as
   OTLP-ish ndjson;
3. ``kftpu_edge_shed_total{class}`` reads back through the PR 9
   monitoring tier: registry -> TimeSeriesStore ->
   ``GET /api/metrics/query``.

Exit 0 = every invariant held.
"""

import json
import os
import sys
import tempfile

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from kubeflow_tpu.dashboard.server import DashboardApi          # noqa: E402
from kubeflow_tpu.edge.fleet import (                           # noqa: E402
    FleetEdge,
    FleetRequest,
    FleetRouter,
    ReplicaSim,
    SloAdmissionGate,
    sim_dispatch,
)
from kubeflow_tpu.k8s import FakeKubeClient                     # noqa: E402
from kubeflow_tpu.obs.export import otlp_lines                  # noqa: E402
from kubeflow_tpu.obs.trace import SpanCollector, Tracer        # noqa: E402
from kubeflow_tpu.obs.tsdb import TimeSeriesStore               # noqa: E402
from kubeflow_tpu.utils import DEFAULT_REGISTRY                 # noqa: E402

PAGE = 4


def check(ok, msg):
    if not ok:
        print(f"FAIL: {msg}")
        sys.exit(1)
    print(f"ok: {msg}")


def main() -> None:
    t = [1000.0]

    def clock():
        t[0] += 0.125
        return t[0]

    collector = SpanCollector()
    tracer = Tracer(collector, clock=clock)
    sims = {f"r{i}": ReplicaSim(f"r{i}", page_size=PAGE)
            for i in range(3)}
    router = FleetRouter(page_size=PAGE)
    router.sync({name: f"http://{name}" for name in sims})
    gate = SloAdmissionGate()
    edge = FleetEdge(router, gate, dispatch=sim_dispatch(sims),
                     tracer=tracer)

    # -- 1. warm a prefix, then stream: warm replica out-hits cold ----
    prefix = np.arange(3 * PAGE, dtype=np.int32)
    code, _ = edge.handle(FleetRequest(prompt=prefix,
                                       prefix_len=prefix.size))
    check(code == 200, "warm pass served")
    warm_replica = next(name for name, s in sims.items() if s.requests)
    rng = np.random.default_rng(3)
    for i in range(12):
        # the warmed prefix with fresh suffixes, interleaved with
        # one-off prompts that land wherever
        suffix = rng.integers(500, 900, size=PAGE // 2)
        code, _ = edge.handle(FleetRequest(
            prompt=np.concatenate([prefix, suffix]).astype(np.int32),
            prefix_len=prefix.size))
        check(code == 200, f"warm-prefix request {i} served")
        code, _ = edge.handle(FleetRequest(
            prompt=rng.integers(2000, 3000,
                                size=2 * PAGE).astype(np.int32)))
        check(code == 200, f"one-off request {i} served")

    def hit_rate(sim):
        n = sim.prefix_hits + sim.prefix_misses
        return sim.prefix_hits / n if n else 0.0

    warm_rate = hit_rate(sims[warm_replica])
    cold_rates = [hit_rate(s) for name, s in sims.items()
                  if name != warm_replica]
    check(all(warm_rate > c for c in cold_rates),
          f"warm replica hit-rate {warm_rate:.2f} beats cold "
          f"{[round(c, 2) for c in cold_rates]}")

    # -- 2. overload burst at 2x capacity: shed/served in ONE trace --
    # capacity: each replica admits its slot count; the burst is 2x
    slots = 4
    for name in sims:
        # the scraped telemetry mid-burst: admission queues at ~full
        # page pressure (0.95: batch and standard shed, interactive
        # holds — shed-before-collapse, not shed-everything)
        gate.observe_snapshot(name, {"pages_total": 100, "pages_free": 5,
                                     "slots": slots, "pending": 0})
    classes = ["interactive", "standard", "batch"]
    burst_n = 2 * slots * len(sims)
    outcomes = {c: [] for c in classes}
    with tracer.span("edge.burst", attrs={"requests": burst_n}) as root:
        for i in range(burst_n):
            cls = classes[i % len(classes)]
            code, _ = edge.handle(FleetRequest(
                prompt=np.arange(2 * PAGE),
                headers={"X-Kftpu-Slo-Class": cls}))
            outcomes[cls].append(code)
    check(set(outcomes["interactive"]) == {200},
          "interactive class served through the burst")
    check(set(outcomes["batch"]) == {503},
          "batch class shed through the burst")
    check(set(outcomes["standard"]) == {503},
          "standard class shed at pressure 0.95")
    trace = collector.trace(root.trace_id)
    sheds = [s for s in trace if s.name == "edge.shed"]
    served = [s for s in trace if s.name == "edge.fleet.request"
              and s.attrs.get("http.status") == 200]
    check(sheds and served,
          f"one trace ({root.trace_id}) shows the shed/served split: "
          f"{len(served)} served, {len(sheds)} shed")
    check(all(s.attrs["slo.class"] in ("batch", "standard")
              for s in sheds), "every shed span names a sheddable class")
    artifact = os.path.join(tempfile.mkdtemp(prefix="edge_smoke_"),
                            "burst_trace.ndjson")
    with open(artifact, "w") as f:
        f.write(otlp_lines(trace))
    print(f"trace artifact: {artifact} ({len(trace)} spans)")

    # -- 3. shed counter reads back through tsdb + query API ----------
    store = TimeSeriesStore(clock=clock)
    store.sample_registry(DEFAULT_REGISTRY)
    api = DashboardApi(FakeKubeClient(), tsdb=store, edge=edge)
    code, body = api.handle(
        "GET", "/api/metrics/query?metric=kftpu_edge_shed_total"
               "&label=class:batch", None)
    check(code == 200 and body.get("result"),
          "kftpu_edge_shed_total{class=batch} answers via "
          "/api/metrics/query")
    check(body["result"][0]["value"] >= len(outcomes["batch"]),
          f"queried shed count {body['result'][0]['value']} covers the "
          f"burst's {len(outcomes['batch'])}")
    code, view = api.handle("GET", "/api/metrics/edge", None)
    check(code == 200 and view["shed"].get("batch"),
          "fleet panel route serves the shed split")
    print(json.dumps({"warm_replica": warm_replica,
                      "warm_hit_rate": round(warm_rate, 3),
                      "served": len(served), "shed": len(sheds)}))
    print("edge smoke: PASS")


if __name__ == "__main__":
    main()
