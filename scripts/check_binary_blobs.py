#!/usr/bin/env python3
"""Fail if a large binary is staged for commit (PERF.md artifact policy).

Raw profiler blobs and similar artifacts belong in artifact storage,
not git: once committed they grow every clone forever. This check walks
the *staged* tree (``git diff --cached``) and fails on any added or
modified file that is binary and larger than the threshold (default
1 MB, override with ``--max-bytes``).

Use as a pre-commit hook or CI step:

    python scripts/check_binary_blobs.py            # staged changes
    python scripts/check_binary_blobs.py --ref HEAD~1   # a commit range
"""

from __future__ import annotations

import argparse
import subprocess
import sys

DEFAULT_MAX_BYTES = 1 << 20  # 1 MB


def _git(*args: str) -> str:
    return subprocess.run(["git", *args], check=True,
                          capture_output=True, text=True).stdout


def staged_paths(ref: str | None) -> list[str]:
    base = ["diff", "--cached"] if ref is None else ["diff", ref]
    out = _git(*base, "--name-only", "--diff-filter=AM", "-z")
    return [p for p in out.split("\0") if p]


def is_binary(path: str) -> bool:
    """Git's own heuristic: a NUL byte in the first block = binary."""
    try:
        blob = subprocess.run(
            ["git", "cat-file", "blob", f":{path}"], check=True,
            capture_output=True).stdout[:8192]
    except subprocess.CalledProcessError:
        # not in the index (e.g. --ref mode): read the worktree
        try:
            with open(path, "rb") as f:
                blob = f.read(8192)
        except OSError:
            return False
    return b"\0" in blob


def staged_size(path: str) -> int:
    try:
        out = subprocess.run(["git", "cat-file", "-s", f":{path}"],
                             check=True, capture_output=True,
                             text=True).stdout
        return int(out.strip())
    except (subprocess.CalledProcessError, ValueError):
        import os

        try:
            return os.path.getsize(path)
        except OSError:
            return 0


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--max-bytes", type=int, default=DEFAULT_MAX_BYTES)
    ap.add_argument("--ref", default=None,
                    help="diff against this ref instead of the index")
    args = ap.parse_args(argv)

    offenders = []
    for path in staged_paths(args.ref):
        size = staged_size(path)
        if size > args.max_bytes and is_binary(path):
            offenders.append((path, size))
    if offenders:
        print("ERROR: large binary files staged for commit "
              f"(limit {args.max_bytes} bytes):", file=sys.stderr)
        for path, size in offenders:
            print(f"  {path}  ({size / 1e6:.1f} MB)", file=sys.stderr)
        print("Raw profiler/trace blobs belong in artifact storage "
              "(see PERF.md 'Trace artifact policy').", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
