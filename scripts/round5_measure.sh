#!/bin/bash
# Round-5 measurement runbook — run the moment the device transport
# answers (probe first: timeout 60 python -c "import jax; print(jax.devices())").
# Produces: bench JSON (all 8 configs, decode-engine in 3 sampler modes),
# traces/r05/{resnet50,bert,longcontext,decode,decode_engine},
# act-compress A/B, PERF.md-ready trace-top tables.
set -u
cd "$(dirname "$0")/.."

echo "== probe =="
timeout 90 python -c "import jax; print(jax.devices())" || exit 1

echo "== full bench + traces =="
python bench.py --profile traces/r05 | tee /tmp/bench_r05.json

echo "== act-compress A/B (resnet50 only) =="
KFTPU_RESNET_ACT_COMPRESS=1 python -m kubeflow_tpu.bench.suite resnet50 \
  | tee /tmp/resnet_actcompress.json

echo "== trace tables (paste into PERF.md) =="
for d in traces/r05/*/; do
  echo "--- $d"; python -m kubeflow_tpu.cli trace-top "$d" --top 12 || true
done

echo "Done. Commit traces/r05 + update PERF.md with measured verdicts:"
echo "  - resnet50 act-compress: keep (>=2900 img/s) or reject with step-time data"
echo "  - decode_engine: ms/token + tokens/s at batch 32 vs the 0.41 ms/token floor"
echo "  - sampled bounded vs exact-sort tokens/s at slots=32 (kept/rejected)"
