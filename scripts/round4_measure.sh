#!/bin/bash
# Round-4 measurement runbook — run the moment the device transport
# answers (probe first: timeout 60 python -c "import jax; print(jax.devices())").
# Produces: bench JSON (all 8 configs), traces/r04/*, act-compress A/B.
set -u
cd "$(dirname "$0")/.."

echo "== probe =="
timeout 90 python -c "import jax; print(jax.devices())" || exit 1

echo "== full bench + traces =="
python bench.py --profile traces/r04 | tee /tmp/bench_r04.json

echo "== act-compress A/B (resnet50 only) =="
KFTPU_RESNET_ACT_COMPRESS=1 python -m kubeflow_tpu.bench.suite resnet50 \
  | tee /tmp/resnet_actcompress.json

echo "== trace tables =="
for d in traces/r04/*/; do
  echo "--- $d"; python -m kubeflow_tpu.cli trace-top "$d" --top 12 || true
done

echo "Done. Commit traces/r04 + update PERF.md with the numbers."
