"""Chip experiment: speculative decoding speedup on the bench LM.

Builds the decode bench's 167M-param target (d1024/L8), TRAINS it
briefly on a mixed deterministic/noise next-token task (a random-init
target's logits are near-uniform, so every argmax is a bf16 coin flip
between programs and acceptance measures ~0 regardless of draft
quality), distills a 2-layer draft from the trained target's own
generations (`train/distill.py:make_draft` — the productized recipe),
then measures single-stream FUSED greedy decode vs FUSED speculative
decode wall tok/s at several draft_len k. Speculation is the LATENCY
lever (the engine is the throughput lever), so batch 1 is the honest
configuration. Prints JSON lines for PERF.md.

The deterministic fraction of the task (SPEC_DET_FRAC, default 0.8)
sets the ceiling on acceptance: predictable tokens the draft can learn
vs noise tokens nobody can — a dial for the acceptance regime.
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import jax
import jax.numpy as jnp
import optax

from kubeflow_tpu.models import Transformer, TransformerConfig
from kubeflow_tpu.models.decode import (generate,
                                        speculative_generate_jit)
from kubeflow_tpu.train.distill import make_draft


def _task_batch(rng, batch, seq_len, vocab, det_frac):
    """Sequences where each next token is a fixed affine map of the
    previous with prob det_frac, else uniform noise — over a SMALL
    active vocabulary (256 ids), so the map is learnable in a few
    hundred steps (a full 32k permutation is not)."""
    active = min(256, vocab)
    toks = np.zeros((batch, seq_len), np.int64)
    toks[:, 0] = rng.integers(0, active, batch)
    det = rng.random((batch, seq_len)) < det_frac
    noise = rng.integers(0, active, (batch, seq_len))
    for t in range(1, seq_len):
        mapped = (toks[:, t - 1] * 31 + 7) % active
        toks[:, t] = np.where(det[:, t], mapped, noise[:, t])
    return jnp.asarray(toks.astype(np.int32))


def main():
    prompt_len, new_tokens = 128, 128
    # +16 slack: speculation needs room for in-flight draft proposals
    config = TransformerConfig(
        vocab_size=32000, d_model=1024, n_layers=8, n_heads=16,
        n_kv_heads=16, d_ff=4096,
        max_seq_len=prompt_len + new_tokens + 16, remat=False)
    model = Transformer(config)
    rng = np.random.default_rng(0)
    params = jax.jit(model.init)(
        jax.random.key(1), jnp.zeros((1, 2), jnp.int32))["params"]

    # -- train the target so its argmax is peaked, not a coin flip ----
    det_frac = float(os.environ.get("SPEC_DET_FRAC", "0.8"))
    train_steps = int(os.environ.get("SPEC_TRAIN_STEPS", "150"))
    tx = optax.adamw(3e-4)
    opt_state = tx.init(params)

    @jax.jit
    def train_step(params, opt_state, tokens):
        def loss_fn(p):
            logits = model.apply({"params": p}, tokens[:, :-1])
            logp = jax.nn.log_softmax(logits.astype(jnp.float32), -1)
            nll = -jnp.take_along_axis(
                logp, tokens[:, 1:, None], axis=-1)
            return jnp.mean(nll)

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    t0 = time.perf_counter()
    loss = None
    for _ in range(train_steps):
        batch = _task_batch(rng, 8, 128, config.vocab_size, det_frac)
        params, opt_state, loss = train_step(params, opt_state, batch)
    print(json.dumps({"phase": "train_target", "steps": train_steps,
                      "det_frac": det_frac,
                      "final_loss": round(float(loss), 3),
                      "wall_s": round(time.perf_counter() - t0, 1)}),
          flush=True)

    prompt = _task_batch(rng, 1, prompt_len, config.vocab_size,
                         det_frac)

    t0 = time.perf_counter()
    draft_config, draft_params, stats = make_draft(
        config, params, n_layers=int(os.environ.get("SPEC_DRAFT_LAYERS",
                                                    "2")),
        distill_steps=int(os.environ.get("SPEC_DISTILL_STEPS", "150")))
    print(json.dumps({"phase": "distill",
                      "kl_first": round(stats["first_loss"], 3),
                      "kl_last": round(stats["last_loss"], 3),
                      "draft_layers": stats["n_layers"],
                      "wall_s": round(time.perf_counter() - t0, 1)}),
          flush=True)

    # baseline: plain greedy, ONE compiled program (params as a jit
    # ARGUMENT — closed-over params would embed 334 MB of constants)
    gen = jax.jit(lambda pr, pt: generate(config, pr, pt,
                                          max_new_tokens=new_tokens))
    np.asarray(gen(params, prompt))  # warm + force
    t0 = time.perf_counter()
    base = np.asarray(gen(params, prompt))
    base_dt = time.perf_counter() - t0
    print(json.dumps({"phase": "baseline_greedy",
                      "tokens_per_sec": round(new_tokens / base_dt, 1),
                      "ms_per_token": round(base_dt / new_tokens * 1e3,
                                            2)}), flush=True)

    for k in [int(a) for a in sys.argv[1:]] or [4, 8]:
        toks, st = speculative_generate_jit(
            config, params, draft_config, draft_params, prompt,
            max_new_tokens=new_tokens, draft_len=k)
        np.asarray(toks)  # warm + force
        t0 = time.perf_counter()
        toks, st = speculative_generate_jit(
            config, params, draft_config, draft_params, prompt,
            max_new_tokens=new_tokens, draft_len=k)
        toks = np.asarray(toks)
        dt = time.perf_counter() - t0
        exact = bool((toks == base).all())
        acc = float(st["accepted"]) / max(1.0, float(st["draft_tokens"]))
        print(json.dumps({
            "phase": f"speculative_k{k}",
            "tokens_per_sec": round(new_tokens / dt, 1),
            "ms_per_token": round(dt / new_tokens * 1e3, 2),
            "acceptance": round(acc, 3),
            "rounds": int(st["rounds"]),
            "speedup_vs_greedy": round(base_dt / dt, 2),
            "token_identical": exact}), flush=True)


if __name__ == "__main__":
    main()
