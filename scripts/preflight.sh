#!/usr/bin/env bash
# Preflight gate: every check a PR must pass before review, one command.
#
#   scripts/preflight.sh            # tpulint + staged-blob check
#   scripts/preflight.sh --ref HEAD~1   # blob check over a commit range
#
# Checks:
#   1. tpulint (scripts/run_tpulint.py): rules TPU001-TPU018 over
#      kubeflow_tpu/ — the AST rules, the SPMD shardlint plane
#      (TPU006-TPU009), the lock-discipline dataflow plane
#      (TPU010-TPU012), TPU013 metric-contract, and the trace-taint
#      compile plane: TPU014 traced-control-flow, TPU015
#      recompile-hazard, TPU016 use-after-donate, TPU017
#      host-sync-in-hot-path, TPU018 unledgered-compile — gated on
#      tpulint_baseline.json (docs/ANALYSIS.md). Writes the SARIF
#      artifact to traces/tpulint.sarif on every run; --budget-check
#      ASSERTS the full 18-rule wall stays within +25% of the
#      TPU001-TPU013 reference pass and emits the measured delta into
#      the SARIF run properties (budget_delta_pct)
#   1b. compile audit (optional): when a ledger artifact exists at
#      traces/compile_events.json (CompileLedger.events_payload()
#      dump), join it against the static jit-site inventory and fail
#      on recompile storms; silently skipped when absent
#   2. binary-blob guard (scripts/check_binary_blobs.py): no large
#      binaries staged for commit (PERF.md trace-artifact policy)
#   3. obs smoke test (tests/test_obs.py): traceparent round-trip, span
#      propagation proxy->server->engine, /api/traces, histograms
#      (docs/OBSERVABILITY.md)
#   4. training-telemetry smoke test (tests/test_step_telemetry.py):
#      step clock + MFU/recompile accounting, flight-recorder dumps,
#      beacons -> operator straggler status -> dashboard
#      /api/jobs/<ns>/<name>/telemetry (docs/OBSERVABILITY.md
#      training-plane section)
#   5. paged-engine smoke (scripts/paged_smoke.py): admit -> chunked
#      prefill -> decode -> retire on CPU, prefix pages shared by
#      refcount and every refcount back to zero, in TWO passes — the
#      gather (bit-parity oracle) path, then the Pallas paged-attention
#      kernel path (interpret mode) with a copy-on-write boundary-page
#      split asserted to copy exactly once (docs/SERVING.md)
#   6. scheduler-plane smoke (scripts/scheduler_smoke.py): fake 4-slice
#      inventory, two gangs admit under tenant quota, a high-priority
#      gang preempts the minimum-cost victim (checkpointed exactly
#      once, Preempted condition, head-of-queue requeue, resume with
#      the step clock intact) and every chip stays accounted for
#      (docs/SCHEDULER.md)
#   7. monitoring/alerts smoke (scripts/alerts_smoke.py): fake-clock
#      end-to-end — scrape two fake targets into the tsdb, inject a
#      5xx burst, assert the burn-rate SLO rule walks
#      Pending -> Firing -> Resolved with exactly one Event per
#      transition and the firing gauge back at 0
#      (docs/OBSERVABILITY.md, Monitoring section)
#   8. elastic-training smoke (scripts/elastic_smoke.py): a fake
#      4-slice gang trains to step 50, shrinks to 2 slices through
#      snapshot-reshard-resume (exactly one save, spans in order),
#      trains to 100, and the loss stream matches a never-resized
#      oracle after the resync step (docs/ELASTIC.md)
#   9. fleet-edge smoke (scripts/edge_smoke.py): fake 3-replica fleet —
#      prefix-affinity routing concentrates a warmed prefix (warm
#      replica hit-rate > cold), an overload burst at 2x capacity
#      sheds lowest-SLO-class-first with the shed/served split in ONE
#      trace, and kftpu_edge_shed_total{class} reads back through the
#      tsdb + /api/metrics/query (docs/EDGE.md)
#  10. goodput-ledger smoke (scripts/goodput_smoke.py): a fake 2-slice
#      elastic job queues, trains, gets preempted, resumes, and
#      shrinks; status.goodput shows queue_wait/preempted/resizing/
#      checkpoint_save/restore, fractions sum to 1.0, intervals tile
#      the wall clock, the counter reads back through the tsdb, and
#      job-badput-burn walks Pending -> Firing -> Resolved on an
#      injected checkpoint stall (docs/OBSERVABILITY.md "Goodput")
#  11. tile-table validate (scripts/tile_sweep.py --validate): strict
#      legality over every committed kubeflow_tpu/ops/tile_table.json
#      entry (divisibility, analytic VMEM estimate, dtype-lane
#      legality) plus a CPU-tier parity smoke running the three flash
#      kernels and the paged kernel with every committed tile config
#      against the default-tile oracle — a bad table edit fails here
#      before a bench round burns chip time (PERF.md "Tile autotune")
#  12. compile/HBM profile smoke (scripts/profile_smoke.py): a live
#      jax.jit compile lands in the CompileLedger via jax.monitoring
#      exactly once, timed_compile fingerprints the HLO + records the
#      memory_analysis budget, the CPU HbmSampler degrades silently,
#      and on a fake clock injected compile events become the goodput
#      ledger's ground truth (startup_compile == event-sourced seconds
#      exactly), kftpu_compile_seconds reads back through the tsdb +
#      /api/metrics/query, /api/jobs/<ns>/<name>/profile serves the
#      summary, and an injected HBM climb walks hbm-headroom
#      Pending -> Firing -> Resolved with one Event per transition
#      (docs/OBSERVABILITY.md "Compile & memory")
#  13. request-lifecycle smoke (scripts/request_smoke.py): a mixed
#      burst rides edge->engine on CPU with traceparents; every
#      record's phases tile [submit, end] exactly, each request is ONE
#      trace tree (edge + engine spans under the inbound trace id),
#      kftpu_request_ttft_ms reads back through the tsdb +
#      /api/metrics/query, the worst-TTFT exemplar resolves through
#      /api/traces/<id>, and ttft-slo-burn walks
#      Pending -> Firing -> Resolved on an injected breach storm with
#      one Event per transition (docs/OBSERVABILITY.md
#      "Request lifecycle")
set -euo pipefail
cd "$(dirname "$0")/.."

rc=0

echo "== preflight: tpulint =="
python scripts/run_tpulint.py --budget-check \
    --sarif-out traces/tpulint.sarif || rc=1

if [ -f traces/compile_events.json ]; then
    echo "== preflight: compile audit =="
    python scripts/run_tpulint.py \
        --compile-audit traces/compile_events.json || rc=1
else
    echo "== preflight: compile audit (skipped: no traces/compile_events.json) =="
fi

echo "== preflight: binary blobs =="
python scripts/check_binary_blobs.py "$@" || rc=1

echo "== preflight: obs smoke test =="
JAX_PLATFORMS=cpu python -m pytest tests/test_obs.py -q -m 'not slow' \
    -p no:cacheprovider || rc=1

echo "== preflight: training-telemetry smoke test =="
JAX_PLATFORMS=cpu python -m pytest tests/test_step_telemetry.py -q \
    -m 'not slow' -p no:cacheprovider || rc=1

echo "== preflight: paged decode engine smoke =="
JAX_PLATFORMS=cpu python scripts/paged_smoke.py || rc=1

echo "== preflight: scheduler plane smoke =="
JAX_PLATFORMS=cpu python scripts/scheduler_smoke.py || rc=1

echo "== preflight: monitoring/alerts smoke =="
JAX_PLATFORMS=cpu python scripts/alerts_smoke.py || rc=1

echo "== preflight: elastic training smoke =="
JAX_PLATFORMS=cpu XLA_FLAGS="${XLA_FLAGS:-} --xla_force_host_platform_device_count=8" \
    python scripts/elastic_smoke.py || rc=1

echo "== preflight: fleet serving edge smoke =="
JAX_PLATFORMS=cpu python scripts/edge_smoke.py || rc=1

echo "== preflight: goodput ledger smoke =="
JAX_PLATFORMS=cpu python scripts/goodput_smoke.py || rc=1

echo "== preflight: tile table validate =="
JAX_PLATFORMS=cpu python scripts/tile_sweep.py --validate || rc=1

echo "== preflight: compile/HBM profile smoke =="
JAX_PLATFORMS=cpu python scripts/profile_smoke.py || rc=1

echo "== preflight: request lifecycle smoke =="
JAX_PLATFORMS=cpu python scripts/request_smoke.py || rc=1

if [ "$rc" -ne 0 ]; then
    echo "preflight: FAILED" >&2
else
    echo "preflight: ok"
fi
exit "$rc"
