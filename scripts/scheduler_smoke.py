#!/usr/bin/env python
"""Scheduler-plane smoke gate (scripts/preflight.sh stage).

Drives the cluster gang queue end-to-end on a fake 4-slice inventory
under a fake clock: two low-priority gangs saturate their tenant's chip
quota, a high-priority gang arrives, the queue preempts the
minimum-cost victim through the operator (checkpoint observed exactly
once, ``Preempted/RequeuedForPriority`` condition set, head-of-queue
requeue), the preemptor places, and at every step the chip ledger must
balance: chips(placed gangs) + chips(free slices) == chips(cluster).
Exits nonzero on any violated invariant (docs/SCHEDULER.md).
"""

import sys
import threading

sys.path.insert(0, ".")

from kubeflow_tpu.k8s import FakeKubeClient  # noqa: E402
from kubeflow_tpu.manifests.components.tpujob_operator import (  # noqa: E402
    API_VERSION,
    TPUJOB_KIND,
)
from kubeflow_tpu.obs.trace import SpanCollector, Tracer  # noqa: E402
from kubeflow_tpu.operators.tpujob import (  # noqa: E402
    JOB_LABEL,
    PreemptionCheckpointer,
    TpuJobOperator,
    tpujob,
)
from kubeflow_tpu.platform.local import fake_slice_nodes  # noqa: E402
from kubeflow_tpu.scheduler.inventory import GangScheduler  # noqa: E402
from kubeflow_tpu.scheduler.queue import (  # noqa: E402
    PLACED,
    PREEMPTING,
    QUEUED,
    GangQueue,
)

CHIPS_PER_HOST = 4


class Clock:
    def __init__(self):
        self.t = 0.0
        self._lock = threading.Lock()

    def __call__(self):
        with self._lock:
            self.t += 0.25
            return self.t


class Checkpointer(PreemptionCheckpointer):
    def __init__(self, steps):
        self.steps = steps
        self.save_calls = []

    def save(self, job):
        name = job["metadata"]["name"]
        self.save_calls.append(name)
        return self.steps.get(name)

    def latest_step(self, ns, name):
        return self.steps.get(name)


def check(ok, what):
    if not ok:
        print(f"scheduler smoke: FAIL — {what}", file=sys.stderr)
        sys.exit(1)
    print(f"  ok: {what}")


def chips_ledger(client, queue, shape="v5e-8"):
    """(chips held by placed gangs, free chips, cluster chips)."""
    inv = GangScheduler(client).inventory(shape)
    total = sum(s.hosts for s in inv) * CHIPS_PER_HOST
    free = sum(s.free_hosts for s in inv) * CHIPS_PER_HOST
    placed = sum(g["chips"] for g in queue.status()["gangs"]
                 if g["state"] in (PLACED, PREEMPTING))
    return placed, free, total


def main():
    client = FakeKubeClient()
    for node in fake_slice_nodes("v5e-8", count=4):
        client.create(node)
    client.create({"apiVersion": "v1", "kind": "ResourceQuota",
                   "metadata": {"name": "profile-quota",
                                "namespace": "tenant"},
                   "spec": {"hard": {"google.com/tpu": "16"}}})
    clock = Clock()
    ckpt = Checkpointer({"low-a": 40, "low-b": 90})
    queue = GangQueue(client, clock=clock,
                      tracer=Tracer(SpanCollector(), clock=clock),
                      checkpoint_step=ckpt.latest_step)
    op = TpuJobOperator(client, clock=clock, queue=queue,
                        checkpointer=ckpt)

    def pods(ns, name):
        return client.list("v1", "Pod", ns,
                           label_selector={JOB_LABEL: name})

    # 1. two low-priority gangs admit under the 16-chip tenant quota
    for name in ("low-a", "low-b"):
        client.create(tpujob(name, "tenant", {"image": "smoke",
                                              "hostsPerSlice": 2}))
        op.reconcile("tenant", name)
        check(len(pods("tenant", name)) == 2, f"{name} placed (2 workers)")
    queue.predictor.observe("tenant", "low-a", steps_per_sec=1.0,
                            last_step=100)
    queue.predictor.observe("tenant", "low-b", steps_per_sec=1.0,
                            last_step=100)
    placed, free, total = chips_ledger(client, queue)
    check(placed + free == total,
          f"chip ledger balances after admits ({placed}+{free}=={total})")

    # 2. a high-priority 3-slice gang cannot fit the 2 free slices
    client.create(tpujob("urgent", "prod", {
        "image": "smoke", "slices": 3, "hostsPerSlice": 2,
        "priority": 10}))
    op.reconcile("prod", "urgent")
    check(queue.state_of("tenant", "low-b") == PREEMPTING,
          "min-cost victim (freshest checkpoint) marked Preempting")

    # 3. the victim checkpoints exactly once and requeues at the head
    op.reconcile("tenant", "low-b")
    check(ckpt.save_calls == ["low-b"], "exactly one checkpoint save")
    check(pods("tenant", "low-b") == [], "victim gang torn down")
    job = client.get(API_VERSION, TPUJOB_KIND, "tenant", "low-b")
    conds = {(c["type"], c["reason"]) for c in job["status"]["conditions"]}
    check(("Preempted", "RequeuedForPriority") in conds,
          "Preempted/RequeuedForPriority condition set")
    check(queue.state_of("tenant", "low-b") == QUEUED,
          "victim requeued (head of its class)")

    # 4. the preemptor lands on the freed capacity; ledger still balances
    op.reconcile("prod", "urgent")
    check(len(pods("prod", "urgent")) == 6, "preemptor placed (6 workers)")
    placed, free, total = chips_ledger(client, queue)
    check(placed + free == total and free == 0,
          f"every chip accounted for ({placed} placed + {free} free "
          f"== {total})")

    # 5. capacity frees; the victim resumes with its step clock intact
    for pod in pods("prod", "urgent"):
        pod.setdefault("status", {})["phase"] = "Succeeded"
        client.update_status(pod)
    op.reconcile("prod", "urgent")
    op.reconcile("tenant", "low-b")
    check(len(pods("tenant", "low-b")) == 2, "victim resumed")
    check(queue.last_checkpoint_step("tenant", "low-b") == 90,
          "step clock intact through preempt-requeue (checkpoint 90)")
    placed, free, total = chips_ledger(client, queue)
    check(placed + free == total,
          f"final chip ledger balances ({placed}+{free}=={total})")
    print("scheduler smoke: ok")


if __name__ == "__main__":
    main()
