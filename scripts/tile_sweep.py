"""Chip experiment: flash/paged tile sweep across seq×head shape classes.

The generalization of ``sync_sweep.py`` for ROADMAP items 1+3: measure
the candidate ``(block_q, block_k)`` grid per shape class ON CHIP — fwd
and fwd+bwd timed separately, skip-on-compile-failure — and emit a
table update for ``kubeflow_tpu/ops/tile_table.json`` plus a JSON
artifact, so the next TPU-attached round regenerates the table from
measurement the same way the bench adjudicates every other lever.
One JSON line per point for PERF.md.

    python scripts/tile_sweep.py                       # sweep, print lines
    python scripts/tile_sweep.py --out sweep.json      # + artifact
    python scripts/tile_sweep.py --update-table        # merge winners
    python scripts/tile_sweep.py --paged               # head-group sweep
    python scripts/tile_sweep.py --validate            # no chip needed

``--validate`` is the preflight stage: strict table legality
(divisibility, VMEM estimate, dtype-lane legality — the same
``autotune.validate_entry`` the loader and TPU001 use) plus a CPU-tier
parity smoke that runs the three flash kernels and the paged kernel
with every committed tile config against the default-tile oracle in
the Pallas interpreter. Exits nonzero on an illegal entry or a parity
break, so a bad table edit fails before a bench round burns chip time.
"""

import argparse
import itertools
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# the r05-anchored shape grid: the three measured longcontext shapes
# (d1024/L8 ≙ head_dim 64 × 16 heads) plus the BERT-base bidirectional
# shape ROADMAP item 3 names
SWEEP_SHAPES = [
    dict(seq=8192, n_heads=16, head_dim=64, causal=True),
    dict(seq=16384, n_heads=16, head_dim=64, causal=True),
    dict(seq=32768, n_heads=16, head_dim=64, causal=True),
    dict(seq=512, n_heads=12, head_dim=64, causal=False),
]
EDGES = (256, 512, 1024, 2048)


def _sync(x):
    import jax

    jax.block_until_ready(x)


def _time_best(fn, warmup: int = 1, iters: int = 3) -> float:
    for _ in range(warmup):
        _sync(fn())
    best = float("inf")
    for _ in range(iters):
        t0 = time.perf_counter()
        _sync(fn())
        best = min(best, time.perf_counter() - t0)
    return best * 1e3


def sweep(args) -> dict:
    import jax
    import jax.numpy as jnp

    from kubeflow_tpu.ops import autotune
    from kubeflow_tpu.ops.attention import flash_attention

    gen = autotune.backend_generation()
    dtype = jnp.bfloat16
    points, winners = [], {}
    seqs = [int(s) for s in args.seq] if args.seq else None
    for shape in SWEEP_SHAPES:
        if seqs and shape["seq"] not in seqs:
            continue
        S, H, D = shape["seq"], shape["n_heads"], shape["head_dim"]
        causal = shape["causal"]
        q, k, v = (jax.random.normal(jax.random.PRNGKey(i), (1, S, H, D),
                                     dtype) for i in range(3))
        nbytes = autotune.DTYPE_BYTES[autotune.dtype_name(dtype)]
        best_fwd, best_bwd = (None, float("inf")), (None, float("inf"))
        for bq, bk in itertools.product(EDGES, EDGES):
            point = {"shape": shape, "block_q": bq, "block_k": bk,
                     "dtype": "bfloat16", "generation": gen}
            if S % min(bq, S) or S % min(bk, S):
                point["skip"] = "blocks do not divide seq"
                print(json.dumps(point), flush=True)
                continue
            vm = max(autotune.flash_vmem_bytes(kname, bq, bk, D, nbytes)
                     for kname in ("flash_fwd", "flash_bwd_dq",
                                   "flash_bwd_dkv"))
            if vm > autotune.VMEM_BUDGET_BYTES:
                point["skip"] = (f"VMEM estimate {vm} over budget "
                                 f"{autotune.VMEM_BUDGET_BYTES}")
                print(json.dumps(point), flush=True)
                continue

            def fwd(q=q, k=k, v=v, bq=bq, bk=bk):
                return flash_attention(q, k, v, causal, bq, bk)

            def fwdbwd(q=q, k=k, v=v, bq=bq, bk=bk):
                return jax.grad(lambda q, k, v: jnp.sum(
                    flash_attention(q, k, v, causal, bq, bk)
                    .astype(jnp.float32) ** 2), argnums=(0, 1, 2))(q, k, v)

            try:
                point["fwd_ms"] = round(_time_best(jax.jit(fwd)), 3)
                point["fwdbwd_ms"] = round(_time_best(jax.jit(fwdbwd)), 3)
            except Exception as e:  # noqa: BLE001 — skip-on-compile-failure
                point["skip"] = f"{type(e).__name__}: {e}"
                print(json.dumps(point), flush=True)
                continue
            print(json.dumps(point), flush=True)
            points.append(point)
            if point["fwd_ms"] < best_fwd[1]:
                best_fwd = ((bq, bk), point["fwd_ms"])
            if point["fwdbwd_ms"] < best_bwd[1]:
                best_bwd = ((bq, bk), point["fwdbwd_ms"])
        skey = f"s{S}/{'causal' if causal else 'bidir'}"
        if best_fwd[0]:
            winners[skey] = {"shape": shape, "fwd": best_fwd,
                             "fwdbwd": best_bwd}
    return {"generation": gen, "points": points, "winners": winners}


def sweep_paged(args) -> dict:
    import jax
    import jax.numpy as jnp

    from kubeflow_tpu.ops import autotune
    from kubeflow_tpu.ops.paged_attention import paged_decode_attention

    gen = autotune.backend_generation()
    B, QH, KH, Dh, ps = 32, 16, 16, 64, 64
    n_log, P = 32, 256
    q = jax.random.normal(jax.random.PRNGKey(0), (B, QH, Dh), jnp.bfloat16)
    kp = jax.random.normal(jax.random.PRNGKey(1), (P, ps, KH, Dh),
                           jnp.bfloat16)
    vp = jax.random.normal(jax.random.PRNGKey(2), (P, ps, KH, Dh),
                           jnp.bfloat16)
    pages = jax.random.randint(jax.random.PRNGKey(3), (B, n_log), 0, P)
    pos = jax.random.randint(jax.random.PRNGKey(4), (B,), ps,
                             n_log * ps - 1)
    points, best = [], (1, float("inf"))
    hb = 1
    while hb <= KH:
        point = {"paged": True, "head_block": hb, "generation": gen,
                 "page_size": ps, "n_kv_heads": KH}
        try:
            ms = _time_best(jax.jit(
                lambda hb=hb: paged_decode_attention(q, kp, vp, pages, pos,
                                                     head_block=hb)))
            point["step_ms"] = round(ms, 3)
            points.append(point)
            if ms < best[1]:
                best = (hb, ms)
        except Exception as e:  # noqa: BLE001 — skip-on-compile-failure
            point["skip"] = f"{type(e).__name__}: {e}"
        print(json.dumps(point), flush=True)
        hb *= 2
    return {"generation": gen, "points": points,
            "winner": {"head_block": best[0], "step_ms": best[1],
                       "n_kv_heads": KH, "page_size": ps}}


def update_table(result: dict, paged_result: dict, path: str) -> None:
    """Merge sweep winners into the committed table: one entry per
    (kernel key, shape class), fwd winner → flash_fwd, fwd+bwd winner →
    the two backward keys (timed jointly by construction)."""
    from kubeflow_tpu.ops import autotune

    table = autotune.load_table(path) if os.path.exists(path) else (
        autotune.TileTable([], [], path=path))
    gen = (result or paged_result)["generation"]

    def put(entry):
        errs = autotune.validate_entry(entry)
        if errs:
            print(f"tile_sweep: refusing illegal winner "
                  f"{autotune.entry_key(entry)}: {errs}", file=sys.stderr)
            return
        table.entries = [e for e in table.entries
                         if not all(e.get(f) == entry.get(f)
                                    for f in ("kernel", "seq_bucket",
                                              "dtype", "causal",
                                              "generation", "head_dim"))]
        table.entries.append(entry)

    for w in (result or {}).get("winners", {}).values():
        shape = w["shape"]
        base = dict(seq_bucket=autotune.seq_bucket(shape["seq"]),
                    head_dim=shape["head_dim"], n_heads=shape["n_heads"],
                    n_kv_heads=None, dtype="bfloat16",
                    causal=shape["causal"], generation=gen)
        (bq, bk), ms = w["fwd"]
        put(dict(kernel="flash_fwd", block_q=bq, block_k=bk,
                 provenance=f"tile_sweep {gen}: fwd {ms} ms", **base))
        (bq, bk), ms = w["fwdbwd"]
        for kname in ("flash_bwd_dq", "flash_bwd_dkv"):
            put(dict(kernel=kname, block_q=bq, block_k=bk,
                     provenance=f"tile_sweep {gen}: fwd+bwd {ms} ms",
                     **base))
    if paged_result:
        w = paged_result["winner"]
        put(dict(kernel="paged_attn", seq_bucket=None, head_dim=None,
                 n_heads=None, n_kv_heads=w["n_kv_heads"],
                 page_size=w["page_size"], dtype="bfloat16", causal=None,
                 generation=gen, head_block=w["head_block"],
                 provenance=f"tile_sweep {gen}: decode step "
                            f"{round(w['step_ms'], 3)} ms"))
    autotune.save_table(table, path)
    print(f"tile_sweep: wrote {len(table.entries)} entries to {path}")


# ---------------------------------------------------------------------------
# --validate: table legality + CPU-tier parity smoke (preflight stage)
# ---------------------------------------------------------------------------


def _flash_parity(entry, autotune) -> str:
    """Run the three flash kernels with this entry's tiles on a small
    shape against the default-tile oracle; '' = pass. Small shapes clamp
    every tile to the sequence, so configs whose effective tiles match
    the oracle's must be bit-consistent; larger tiles only reorder the
    online softmax, so the remainder gates at tight tolerance."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from kubeflow_tpu.ops.attention import flash_attention

    causal = bool(entry.get("causal", True))
    S, H, D = 64, 4, 16
    q, k, v = (jax.random.normal(jax.random.PRNGKey(i), (2, S, H, D),
                                 jnp.float32) for i in range(3))
    bq = autotune.fit_block(S, entry["block_q"])
    bk = autotune.fit_block(S, entry["block_k"])
    oracle_b = autotune.fit_block(S, 16)
    try:
        out = flash_attention(q, k, v, causal, bq, bk)
        ref = flash_attention(q, k, v, causal, oracle_b, oracle_b)
        exact = (bq, bk) == (oracle_b, oracle_b)
        if exact and not np.array_equal(np.asarray(out), np.asarray(ref)):
            return "fwd not bit-consistent with the default-tile oracle"
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=1e-5)
        g_out = jax.grad(lambda q, k, v: jnp.sum(flash_attention(
            q, k, v, causal, bq, bk) ** 2), argnums=(0, 1, 2))(q, k, v)
        g_ref = jax.grad(lambda q, k, v: jnp.sum(flash_attention(
            q, k, v, causal, oracle_b, oracle_b) ** 2),
            argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g_out, g_ref):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-4)
    except Exception as e:  # noqa: BLE001 — a parity break IS the verdict
        return f"{type(e).__name__}: {e}"
    return ""


def _paged_parity(entry, autotune) -> str:
    import jax
    import jax.numpy as jnp
    import numpy as np

    from kubeflow_tpu.ops.paged_attention import paged_decode_attention

    B, QH, KH, Dh, ps, P, n_log = 2, 8, 4, 16, 8, 6, 3
    hb = int(entry.get("head_block", 1))
    if KH % hb:
        hb = 1  # the resolve-time degradation; smoke what would run
    q = jax.random.normal(jax.random.PRNGKey(0), (B, QH, Dh), jnp.float32)
    kp = jax.random.normal(jax.random.PRNGKey(1), (P, ps, KH, Dh),
                           jnp.float32)
    vp = jax.random.normal(jax.random.PRNGKey(2), (P, ps, KH, Dh),
                           jnp.float32)
    pages = jnp.array([[0, 1, 2], [3, 4, P]], jnp.int32)
    pos = jnp.array([20, 11], jnp.int32)
    try:
        out = paged_decode_attention(q, kp, vp, pages, pos, head_block=hb)
        ref = paged_decode_attention(q, kp, vp, pages, pos, head_block=1)
        if hb == 1 and not np.array_equal(np.asarray(out), np.asarray(ref)):
            return "head_block=1 not bit-consistent with itself"
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=1e-5)
    except Exception as e:  # noqa: BLE001
        return f"{type(e).__name__}: {e}"
    return ""


def validate(table_path: str) -> int:
    from kubeflow_tpu.ops import autotune

    try:
        table = autotune.load_table(table_path, strict=True)
    except (ValueError, FileNotFoundError) as e:
        print(f"tile_sweep --validate: ILLEGAL table: {e}", file=sys.stderr)
        return 1
    failures = []
    for entry in table.entries:
        check = (_paged_parity if entry["kernel"] == "paged_attn"
                 else _flash_parity)
        err = check(entry, autotune)
        status = err or "ok"
        print(f"  {autotune.entry_key(entry)}: {status}")
        if err:
            failures.append((autotune.entry_key(entry), err))
    # the fallback path must stay parity-clean too: resolve a shape no
    # entry covers and run what resolution returns
    import jax.numpy as jnp

    with autotune.table_override(table):
        cfg = autotune.resolve_flash(
            "flash_fwd", seq=64, head_dim=16, n_heads=4, n_kv_heads=4,
            dtype=jnp.float32, causal=True)
    if cfg.source != "fallback":
        # a table edit covering the probe shape would silently stop
        # exercising the fallback — that is a gate failure, not a note
        print(f"  fallback probe resolved from {cfg.source}, expected "
              "fallback", file=sys.stderr)
        failures.append(("fallback-probe",
                         f"resolved from {cfg.source}"))
    err = _flash_parity({"block_q": cfg.block_q, "block_k": cfg.block_k,
                         "causal": True}, autotune)
    print(f"  fallback({cfg.block_q},{cfg.block_k}): {err or 'ok'}")
    if err:
        failures.append(("fallback", err))
    if failures:
        print(f"tile_sweep --validate: {len(failures)} failure(s)",
              file=sys.stderr)
        return 1
    print(f"tile_sweep --validate: ok ({len(table.entries)} entries, "
          f"{len(table.rejected)} rejected)")
    return 0


def main() -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--validate", action="store_true",
                   help="table legality + CPU parity smoke; no chip")
    p.add_argument("--table", default=None,
                   help="tile table path (default: the committed one)")
    p.add_argument("--seq", nargs="*", default=None,
                   help="restrict the sweep to these seq lens")
    p.add_argument("--paged", action="store_true",
                   help="also sweep the paged kernel's head_block")
    p.add_argument("--out", default=None, help="write the JSON artifact")
    p.add_argument("--update-table", action="store_true",
                   help="merge measured winners into the table")
    args = p.parse_args()

    from kubeflow_tpu.ops import autotune

    table_path = args.table or autotune.DEFAULT_TABLE_PATH
    if args.validate:
        sys.exit(validate(table_path))

    # --seq restricts the flash grid (an empty intersection skips it —
    # the "paged only" spelling is --paged --seq 0)
    result = sweep(args)
    paged_result = sweep_paged(args) if args.paged else None
    artifact = {"flash": result, "paged": paged_result}
    if args.out:
        with open(args.out, "w", encoding="utf-8") as f:
            json.dump(artifact, f, indent=2)
        print(f"tile_sweep: artifact written to {args.out}")
    if args.update_table:
        update_table(result, paged_result, table_path)


if __name__ == "__main__":
    main()
