#!/usr/bin/env python3
"""Run tpulint over the repo; exit nonzero on NEW findings.

Usage:

    python scripts/run_tpulint.py                       # lint kubeflow_tpu/
    python scripts/run_tpulint.py kubeflow_tpu/ops      # lint a subtree
    python scripts/run_tpulint.py --rules TPU001,TPU003
    python scripts/run_tpulint.py --baseline-update     # re-grandfather
    python scripts/run_tpulint.py --show-baselined      # full debt view
    python scripts/run_tpulint.py --format json         # machine output
    python scripts/run_tpulint.py --format sarif        # CI PR annotations

Pre-existing findings live in ``tpulint_baseline.json`` (committed);
only findings beyond the baseline fail the run. After fixing debt, run
``--baseline-update`` so the baseline shrinks with the fix. The rule
catalog and pragma syntax are documented in ``docs/ANALYSIS.md``.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from kubeflow_tpu.analysis import runner  # noqa: E402
from kubeflow_tpu.analysis.registry import all_checkers  # noqa: E402


def sarif_payload(report) -> dict:
    """SARIF 2.1.0 for the *new* (gating) findings — the shape CI
    uploaders expect for inline PR-line annotations. Baselined debt is
    deliberately absent: annotating grandfathered lines on every PR
    would train reviewers to ignore the bot."""
    rules = [
        {"id": rule_id,
         "name": cls.name,
         "shortDescription": {"text": cls.name},
         "defaultConfiguration": {"level": cls.severity}}
        for rule_id, cls in sorted(all_checkers().items())
    ]
    results = []
    for f in report.new:
        text = f.message if not f.hint else f"{f.message} (hint: {f.hint})"
        results.append({
            "ruleId": f.rule,
            "level": f.severity,
            "message": {"text": text},
            "locations": [{
                "physicalLocation": {
                    "artifactLocation": {"uri": f.path,
                                         "uriBaseId": "SRCROOT"},
                    "region": {"startLine": f.line},
                },
            }],
        })
    return {
        "$schema": "https://json.schemastore.org/sarif-2.1.0.json",
        "version": "2.1.0",
        "runs": [{
            "tool": {"driver": {
                "name": "tpulint",
                "informationUri": "docs/ANALYSIS.md",
                "rules": rules,
            }},
            # SRCROOT is deliberately left undefined (no
            # originalUriBaseIds): per SARIF §3.14.14 the consumer —
            # the CI uploader, which knows the checkout root — resolves
            # it; baking in a wrong absolute root would break PR-line
            # annotation placement on every machine but this one
            "results": results,
        }],
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("paths", nargs="*", default=None,
                    help="files/dirs to lint (default: kubeflow_tpu)")
    ap.add_argument("--rules", default=None,
                    help="comma-separated rule ids (default: all)")
    ap.add_argument("--baseline", default=None,
                    help="baseline path ('' disables; default: "
                         "tpulint_baseline.json at the repo root)")
    ap.add_argument("--baseline-update", action="store_true",
                    help="rewrite the baseline from current findings "
                         "and exit 0")
    ap.add_argument("--show-baselined", action="store_true",
                    help="print grandfathered findings too")
    ap.add_argument("--format", choices=("text", "json", "sarif"),
                    default="text")
    args = ap.parse_args(argv)

    rules = ([r.strip().upper() for r in args.rules.split(",") if r.strip()]
             if args.rules else None)
    if args.baseline_update and (args.paths or rules):
        # a scoped run sees only a subset of findings; rewriting the
        # baseline from it would silently drop every grandfathered
        # entry outside the scope and break the next full run
        print("error: --baseline-update requires a full, unfiltered run "
              "(no paths, no --rules)", file=sys.stderr)
        return 2
    report = runner.run_lint(paths=args.paths or None, rules=rules,
                             baseline_path=args.baseline)

    if args.baseline_update:
        path = runner.update_baseline(report, baseline_path=args.baseline
                                      or None)
        print(f"tpulint: baseline updated with "
              f"{len(report.findings)} finding(s) → {path}")
        return 0

    if args.format == "sarif":
        print(json.dumps(sarif_payload(report), indent=1))
    elif args.format == "json":
        print(json.dumps({
            "files": report.files,
            "suppressed": report.suppressed,
            "baselined": report.baselined,
            "new": [
                {"rule": f.rule, "severity": f.severity, "path": f.path,
                 "line": f.line, "message": f.message, "hint": f.hint}
                for f in report.new],
        }, indent=1))
    else:
        print(report.format(show_baselined=args.show_baselined))
    return 1 if report.new else 0


if __name__ == "__main__":
    sys.exit(main())
