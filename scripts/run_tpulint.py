#!/usr/bin/env python3
"""Run tpulint over the repo; exit nonzero on NEW findings.

Usage:

    python scripts/run_tpulint.py                       # lint kubeflow_tpu/
    python scripts/run_tpulint.py kubeflow_tpu/ops      # lint a subtree
    python scripts/run_tpulint.py --rule TPU010,TPU012  # rule filter
    python scripts/run_tpulint.py --changed-only        # git-diff scope
    python scripts/run_tpulint.py --baseline-update     # re-grandfather
    python scripts/run_tpulint.py --show-baselined      # full debt view
    python scripts/run_tpulint.py --format json         # machine output
    python scripts/run_tpulint.py --format sarif        # CI PR annotations
    python scripts/run_tpulint.py --sarif-out traces/tpulint.sarif
    python scripts/run_tpulint.py --budget-check        # +25% wall gate
    python scripts/run_tpulint.py --compile-audit traces/compile_events.json

Pre-existing findings live in ``tpulint_baseline.json`` (committed);
only findings beyond the baseline fail the run. After fixing debt, run
``--baseline-update`` so the baseline shrinks with the fix. The rule
catalog and pragma syntax are documented in ``docs/ANALYSIS.md``.

Every file parses ONCE per run — all checkers share the ModuleInfo
(AST + indices + the memoized lock-set analysis), so wall time stays
flat as rules accrue; the text output prints the measured wall time
and a per-rule finding-count table, and a failing run prints a
new-vs-baseline diff table naming the rule and file.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from kubeflow_tpu.analysis import baseline as baseline_mod  # noqa: E402
from kubeflow_tpu.analysis import compileaudit  # noqa: E402
from kubeflow_tpu.analysis import runner  # noqa: E402
from kubeflow_tpu.analysis.registry import all_checkers  # noqa: E402
from kubeflow_tpu.analysis.walker import walk_paths  # noqa: E402

# the rule set the +25% wall-time budget is measured against: every
# rule that existed before the trace-taint plane (PR 14's budget,
# re-anchored as the catalog grows)
REFERENCE_RULES = tuple(f"TPU{i:03d}" for i in range(1, 14))
BUDGET_PCT = 25.0


def sarif_payload(report, properties=None) -> dict:
    """SARIF 2.1.0 for the *new* (gating) findings — the shape CI
    uploaders expect for inline PR-line annotations. Baselined debt is
    deliberately absent: annotating grandfathered lines on every PR
    would train reviewers to ignore the bot."""
    rules = [
        {"id": rule_id,
         "name": cls.name,
         "shortDescription": {"text": cls.name},
         "defaultConfiguration": {"level": cls.severity}}
        for rule_id, cls in sorted(all_checkers().items())
    ]
    results = []
    for f in report.new:
        text = f.message if not f.hint else f"{f.message} (hint: {f.hint})"
        results.append({
            "ruleId": f.rule,
            "level": f.severity,
            "message": {"text": text},
            "locations": [{
                "physicalLocation": {
                    "artifactLocation": {"uri": f.path,
                                         "uriBaseId": "SRCROOT"},
                    "region": {"startLine": f.line},
                },
            }],
        })
    run = {
        "tool": {"driver": {
            "name": "tpulint",
            "informationUri": "docs/ANALYSIS.md",
            "rules": rules,
        }},
        # SRCROOT is deliberately left undefined (no
        # originalUriBaseIds): per SARIF §3.14.14 the consumer —
        # the CI uploader, which knows the checkout root — resolves
        # it; baking in a wrong absolute root would break PR-line
        # annotation placement on every machine but this one
        "results": results,
    }
    if properties:
        run["properties"] = properties
    return {
        "$schema": "https://json.schemastore.org/sarif-2.1.0.json",
        "version": "2.1.0",
        "runs": [run],
    }


def changed_python_files(root: str) -> list:
    """Git-diff-derived lint scope: tracked files changed vs HEAD plus
    untracked files, filtered to ``.py`` under the default lint paths
    (the baseline only covers those — linting a never-linted tree from
    a --changed-only run would manufacture 'new' findings)."""
    seen = set()
    for cmd in (["git", "diff", "--name-only", "HEAD"],
                ["git", "ls-files", "--others", "--exclude-standard"]):
        proc = subprocess.run(cmd, capture_output=True, text=True,
                              cwd=root)
        if proc.returncode != 0:
            raise RuntimeError(
                proc.stderr.strip() or f"{' '.join(cmd)} failed")
        seen.update(ln.strip() for ln in proc.stdout.splitlines()
                    if ln.strip())
    return sorted(
        p for p in seen
        if p.endswith(".py")
        and any(p.startswith(d.rstrip("/") + "/")
                for d in runner.DEFAULT_PATHS)
        and os.path.exists(os.path.join(root, p)))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("paths", nargs="*", default=None,
                    help="files/dirs to lint (default: kubeflow_tpu)")
    ap.add_argument("--rules", "--rule", dest="rules", default=None,
                    help="comma-separated rule ids (default: all)")
    ap.add_argument("--changed-only", action="store_true",
                    help="lint only git-changed .py files (vs HEAD, "
                         "plus untracked) under the default lint paths")
    ap.add_argument("--baseline", default=None,
                    help="baseline path ('' disables; default: "
                         "tpulint_baseline.json at the repo root)")
    ap.add_argument("--baseline-update", action="store_true",
                    help="rewrite the baseline from current findings "
                         "and exit 0")
    ap.add_argument("--show-baselined", action="store_true",
                    help="print grandfathered findings too")
    ap.add_argument("--format", choices=("text", "json", "sarif"),
                    default="text")
    ap.add_argument("--sarif-out", default=None, metavar="PATH",
                    help="additionally write the SARIF artifact to "
                         "PATH regardless of --format (CI artifact)")
    ap.add_argument("--budget-check", action="store_true",
                    help="also time a reference pass (rules "
                         f"{REFERENCE_RULES[0]}-{REFERENCE_RULES[-1]}) "
                         f"and fail if the full run exceeds it by more "
                         f"than {BUDGET_PCT:.0f}%% (delta lands in the "
                         "SARIF run properties)")
    ap.add_argument("--compile-audit", default=None, metavar="ARTIFACT",
                    help="audit mode: join the static jit-site "
                         "inventory against a recorded compile-event "
                         "artifact (CompileLedger.events_payload() "
                         "dump or bench artifact) and exit 1 on "
                         "recompile storms; skips the lint gate")
    ap.add_argument("--audit-max-per-shape", type=int, default=None,
                    metavar="N",
                    help="compiles allowed per (module, shape_class, "
                         "generation) before a group is a storm "
                         f"(default {compileaudit.DEFAULT_MAX_PER_SHAPE})")
    args = ap.parse_args(argv)

    if args.compile_audit is not None:
        return run_compile_audit(args)

    rules = ([r.strip().upper() for r in args.rules.split(",") if r.strip()]
             if args.rules else None)
    if args.baseline_update and (args.paths or rules or args.changed_only):
        # a scoped run sees only a subset of findings; rewriting the
        # baseline from it would silently drop every grandfathered
        # entry outside the scope and break the next full run
        print("error: --baseline-update requires a full, unfiltered run "
              "(no paths, no --rules, no --changed-only)",
              file=sys.stderr)
        return 2
    if args.changed_only and args.paths:
        print("error: --changed-only and explicit paths are mutually "
              "exclusive", file=sys.stderr)
        return 2

    paths = args.paths or None
    if args.changed_only:
        try:
            # OSError covers git missing from PATH (FileNotFoundError)
            paths = changed_python_files(runner.repo_root())
        except (RuntimeError, OSError) as e:
            print(f"error: --changed-only needs git: {e}",
                  file=sys.stderr)
            return 2
        if not paths:
            print("tpulint: no changed files under "
                  f"{', '.join(runner.DEFAULT_PATHS)}; nothing to lint")
            return 0

    t0 = time.monotonic()
    try:
        report = runner.run_lint(paths=paths, rules=rules,
                                 baseline_path=args.baseline,
                                 allow_unknown_rules=args.baseline_update)
    except baseline_mod.BaselineRuleGap as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    wall = time.monotonic() - t0

    if args.baseline_update:
        path = runner.update_baseline(report, baseline_path=args.baseline
                                      or None)
        print(f"tpulint: baseline updated with "
              f"{len(report.findings)} finding(s) → {path}")
        return 0

    properties = {"wall_s": round(wall, 3),
                  "rules_active": len(all_checkers()) if rules is None
                  else len(rules)}
    budget_fail = False
    if args.budget_check:
        t1 = time.monotonic()
        runner.run_lint(paths=paths, rules=list(REFERENCE_RULES),
                        baseline_path="")
        ref_wall = time.monotonic() - t1
        delta_pct = ((wall - ref_wall) / ref_wall * 100.0
                     if ref_wall > 0 else 0.0)
        budget_fail = delta_pct > BUDGET_PCT
        properties.update({
            "reference_rules": f"{REFERENCE_RULES[0]}-{REFERENCE_RULES[-1]}",
            "reference_wall_s": round(ref_wall, 3),
            "budget_delta_pct": round(delta_pct, 1),
            "budget_limit_pct": BUDGET_PCT,
        })

    if args.sarif_out:
        parent = os.path.dirname(os.path.abspath(args.sarif_out))
        os.makedirs(parent, exist_ok=True)
        with open(args.sarif_out, "w", encoding="utf-8") as f:
            json.dump(sarif_payload(report, properties), f, indent=1)
            f.write("\n")

    if args.format == "sarif":
        print(json.dumps(sarif_payload(report, properties), indent=1))
    elif args.format == "json":
        print(json.dumps({
            "files": report.files,
            "suppressed": report.suppressed,
            "baselined": report.baselined,
            "wall_s": round(wall, 3),
            "rules": {r: {"findings": t, "new": n}
                      for r, (t, n) in report.rule_counts().items()},
            "new": [
                {"rule": f.rule, "severity": f.severity, "path": f.path,
                 "line": f.line, "message": f.message, "hint": f.hint}
                for f in report.new],
        }, indent=1))
    else:
        print(report.format(show_baselined=args.show_baselined))
        print(report.rule_table())
        print(f"tpulint: wall {wall:.2f}s (single shared parse per "
              f"file across all checkers)")
        if args.budget_check:
            print(f"tpulint: budget {properties['budget_delta_pct']:+.1f}% "
                  f"vs reference {properties['reference_rules']} "
                  f"({properties['reference_wall_s']:.2f}s), limit "
                  f"+{BUDGET_PCT:.0f}%"
                  + (" — OVER BUDGET" if budget_fail else ""))
        if args.sarif_out:
            print(f"tpulint: sarif artifact → {args.sarif_out}")
        if report.new:
            print(report.diff_table())
    if budget_fail and args.format != "text":
        print(f"tpulint: wall-time budget exceeded "
              f"(+{properties['budget_delta_pct']:.1f}% > "
              f"+{BUDGET_PCT:.0f}%)", file=sys.stderr)
    return 1 if (report.new or budget_fail) else 0


def run_compile_audit(args) -> int:
    """``--compile-audit``: static jit-site inventory × recorded
    compile events. Exit 0 clean, 1 on storms, 2 on a bad artifact."""
    try:
        events = compileaudit.load_events_file(args.compile_audit)
    except (OSError, ValueError, json.JSONDecodeError) as e:
        print(f"error: --compile-audit {args.compile_audit}: {e}",
              file=sys.stderr)
        return 2
    paths = args.paths or None
    modules = walk_paths(paths or runner.DEFAULT_PATHS,
                         runner.repo_root())
    sites = compileaudit.site_inventory(modules)
    report = compileaudit.audit(
        events, sites,
        max_per_shape=(args.audit_max_per_shape
                       if args.audit_max_per_shape is not None
                       else compileaudit.DEFAULT_MAX_PER_SHAPE))
    print(report.format())
    return 1 if report.storms else 0


if __name__ == "__main__":
    sys.exit(main())
