#!/usr/bin/env python
"""Monitoring-plane smoke gate (scripts/preflight.sh stage).

Drives the monitoring core end-to-end on a fake clock: a scraper pulls
two fake component targets (an edge-proxy-shaped registry and an
engine-shaped one) into the in-process time-series store, a 5xx burst
is injected into the edge traffic, and the burn-rate SLO rule must walk
``Pending -> Firing -> Resolved`` with exactly one k8s Event per
transition and the ``kftpu_alerts_firing`` gauge back at 0 when the
bleeding stops (docs/OBSERVABILITY.md, Monitoring section). Exits
nonzero on any violated invariant.
"""

import sys

sys.path.insert(0, ".")

from kubeflow_tpu.k8s import FakeKubeClient  # noqa: E402
from kubeflow_tpu.obs import alerts as alerts_mod  # noqa: E402
from kubeflow_tpu.obs.alerts import (  # noqa: E402
    FIRING,
    INACTIVE,
    PENDING,
    RESOLVED,
    AlertManager,
    BurnRateRule,
    BurnWindow,
)
from kubeflow_tpu.obs.scrape import Scraper  # noqa: E402
from kubeflow_tpu.obs.trace import SpanCollector, Tracer  # noqa: E402
from kubeflow_tpu.obs.tsdb import TimeSeriesStore  # noqa: E402
from kubeflow_tpu.utils.metrics import Registry  # noqa: E402


class Clock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


def check(ok, what):
    if not ok:
        print(f"FAIL: {what}")
        sys.exit(1)
    print(f"ok: {what}")


def main():
    clock = Clock()
    collector = SpanCollector()

    edge = Registry()
    lat = edge.histogram("request_latency_seconds", "edge latency",
                         buckets=(0.1, 0.5, 2.0))
    engine = Registry()
    engine.gauge("kftpu_engine_kv_pages_free", "free").set(64.0, model="m")

    store = TimeSeriesStore(clock=clock)
    scraper = Scraper(
        store,
        targets={"edge": "http://edge:1/metrics",
                 "engine": "http://engine:2/metrics"},
        clock=clock,
        fetch=lambda url: (edge if "edge" in url else engine).expose())

    kube = FakeKubeClient()
    rule = BurnRateRule(
        name="smoke-slo-burn",
        numerator="request_latency_seconds_count",
        numerator_labels={"code": "5*"},
        denominator="request_latency_seconds_count",
        objective=0.99,
        windows=(BurnWindow(60.0, 20.0, 2.0),),
        for_s=20.0,
        summary="edge 5xx burn")
    mgr = AlertManager(store, [rule], client=kube, namespace="monitoring",
                       clock=clock, tracer=Tracer(collector, clock=clock))

    def state():
        return mgr.status()["rules"][0]["state"]

    def tick(t, n_ok=10, n_5xx=0):
        clock.t = t
        for _ in range(n_ok):
            lat.observe(0.05, route="/predict", code="200")
        for _ in range(n_5xx):
            lat.observe(0.02, route="/predict", code="503")
        scraper.tick()
        mgr.evaluate()

    # healthy traffic
    for i in range(11):
        tick(float(i * 10))
    check(state() == INACTIVE, "healthy traffic leaves the rule inactive")
    ups = dict((labels["target"], p.value)
               for labels, p in store.latest("up"))
    check(ups == {"edge": 1.0, "engine": 1.0},
          "both fake targets scraped up=1")

    # inject the 5xx burst
    tick(110.0, n_ok=5, n_5xx=5)
    tick(120.0, n_ok=5, n_5xx=5)
    check(state() == PENDING, "burst trips the rule into Pending")
    tick(130.0, n_ok=5, n_5xx=5)
    tick(140.0, n_ok=5, n_5xx=5)
    check(state() == FIRING, "for: elapsed -> Firing")
    check(alerts_mod._firing_g.get(rule="smoke-slo-burn") == 1.0,
          "kftpu_alerts_firing gauge at 1 while firing")

    # bleeding stops: the short window clears the rule
    for t in (150.0, 160.0, 170.0):
        tick(t)
    check(state() in (RESOLVED, INACTIVE),
          "healthy traffic resolves the rule")
    check(alerts_mod._firing_g.get(rule="smoke-slo-burn") == 0.0,
          "firing gauge back at 0")

    events = {}
    for e in kube.list("v1", "Event", "monitoring"):
        events.setdefault(e["reason"], []).append(e)
    for reason in ("AlertPending", "AlertFiring", "AlertResolved"):
        check(len(events.get(reason, [])) == 1,
              f"exactly one {reason} Event")
    spans = [s for s in collector.spans() if s.name == "alerts.transition"]
    check([(s.attrs["from"], s.attrs["to"]) for s in spans] == [
        (INACTIVE, PENDING), (PENDING, FIRING), (FIRING, RESOLVED)],
        "one alerts.transition span per transition, in order")

    print("alerts smoke: PASS")


if __name__ == "__main__":
    main()
