#!/usr/bin/env python
"""Request-lifecycle smoke gate (scripts/preflight.sh stage 13).

A mixed burst (interactive + standard, plus a forced batch shed) rides
the real edge->engine path on CPU jax: traceparent-carrying requests
enter ``FleetEdge.handle``, dispatch into an in-process
``DecodeEngine``, and land in ONE shared ``RequestLedger``
(docs/OBSERVABILITY.md "Request lifecycle"). Then checks:

- every finished record's phase intervals tile ``[submit, end]``
  exactly (``check_tiling``), with prefill + decode attribution;
- each request is ONE trace tree: the edge and engine spans all carry
  the inbound trace id, which is also the ledger record id;
- ``kftpu_request_ttft_ms`` reads back through the tsdb and
  ``GET /api/metrics/query``; ``GET /api/models/<m>/requests`` serves
  the worst-TTFT exemplar whose traceId resolves through
  ``GET /api/traces/<id>``;
- the ``ttft-slo-burn-interactive`` burn-rate rule walks
  ``Pending -> Firing -> Resolved`` on an injected breach storm with
  exactly one k8s Event per transition.

Exits nonzero on any violated invariant.
"""

import sys

sys.path.insert(0, ".")

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

from kubeflow_tpu.dashboard.server import DashboardApi  # noqa: E402
from kubeflow_tpu.edge.fleet import (  # noqa: E402
    FleetEdge,
    FleetRequest,
    FleetRouter,
    SloAdmissionGate,
)
from kubeflow_tpu.k8s import FakeKubeClient  # noqa: E402
from kubeflow_tpu.models import (  # noqa: E402
    Transformer,
    TransformerConfig,
)
from kubeflow_tpu.obs import extract, format_traceparent  # noqa: E402
from kubeflow_tpu.obs import requests as reqobs  # noqa: E402
from kubeflow_tpu.obs.alerts import (  # noqa: E402
    FIRING,
    PENDING,
    RESOLVED,
    AlertManager,
    default_rules,
)
from kubeflow_tpu.obs.requests import (  # noqa: E402
    RequestLedger,
    check_tiling,
)
from kubeflow_tpu.obs.trace import (  # noqa: E402
    SpanCollector,
    SpanContext,
    Tracer,
)
from kubeflow_tpu.obs.tsdb import TimeSeriesStore  # noqa: E402
from kubeflow_tpu.serving.engine import DecodeEngine  # noqa: E402
from kubeflow_tpu.utils import DEFAULT_REGISTRY  # noqa: E402


class Clock:
    def __init__(self, now=1000.0):
        self.now = now

    def __call__(self):
        return self.now


def check(ok, what):
    if not ok:
        print(f"FAIL: {what}")
        sys.exit(1)
    print(f"ok: {what}")


def main():
    config = TransformerConfig(vocab_size=97, d_model=32, n_layers=2,
                               n_heads=4, n_kv_heads=2, d_ff=64,
                               max_seq_len=64, dtype=jnp.float32,
                               remat=False)
    params = Transformer(config).init(
        jax.random.key(0), np.zeros((1, 8), np.int32))["params"]

    collector = SpanCollector()
    tracer = Tracer(collector)
    led = RequestLedger()
    eng = DecodeEngine(config, params, slots=2, autostart=False,
                       name="chat", tracer=tracer, request_ledger=led)

    def dispatch(replica, target, request):
        r = eng.submit(list(request.prompt), max_new=4)
        while eng.active_count or eng.pending_count:
            eng.run_once(timeout=0.01)
        return {"tokens": r.result()}

    router = FleetRouter(page_size=4)
    router.sync({"r0": "inproc"})
    gate = SloAdmissionGate()
    edge = FleetEdge(router, gate, dispatch=dispatch, tracer=tracer,
                     request_ledger=led, retry_after_s=1)

    # -- the mixed burst -----------------------------------------------------
    rids = []
    for i, cls in enumerate(["interactive", "standard", "interactive",
                             "standard", "interactive"]):
        inbound = SpanContext(f"{i + 1:02x}" * 16, f"{i + 1:02x}" * 8)
        headers = {"traceparent": format_traceparent(inbound),
                   "X-Kftpu-Slo-Class": cls}
        with tracer.span("edge.http", remote=extract(headers)):
            code, payload = edge.handle(FleetRequest(
                prompt=np.arange(4 + i), headers=headers))
        check(code == 200 and len(payload["tokens"]) == 4,
              f"burst request {i} ({cls}) served 4 tokens")
        rids.append(inbound.trace_id)

    # a pressured gate sheds the batch straggler with a priced 503
    gate.observe_snapshot("r0", {"pages_total": 10, "pages_free": 0,
                                 "slots": 2, "pending": 4})
    edge.note_drain(12, 0.5)
    code, body = edge.handle(FleetRequest(
        prompt=np.arange(4), headers={"X-Kftpu-Slo-Class": "batch"}))
    check(code == 503 and body["retryAfterSeconds"] == 24,
          "batch straggler shed with drain-priced Retry-After")

    # -- phases tile, one trace tree per request -----------------------------
    recs = {r.rid: r for r in led.records()}
    check(len(recs) == 6 and led.live_count() == 0,
          "6 finished records (5 served + 1 shed), none live")
    for rec in recs.values():
        check_tiling(rec)
        check(abs(sum(rec.seconds.values()) - rec.wall_s) < 1e-9,
              f"record {rec.rid[:8]} phases tile the wall clock")
    for rid in rids:
        rec = recs[rid]
        for ph in (reqobs.ADMISSION, reqobs.QUEUE_WAIT, reqobs.PREFILL,
                   reqobs.DECODE):
            check(ph in rec.seconds, f"{rid[:8]} attributes {ph}")
        check(rec.ttft_ms is not None and len(rec.itl_ms) == 3,
              f"{rid[:8]} has TTFT + 3 inter-token gaps")
        names = {s.name for s in collector.spans()
                 if s.trace_id == rid}
        for want in ("edge.http", "edge.fleet.request",
                     "engine.queue_wait", "engine.prefill",
                     "engine.first_token"):
            check(want in names, f"{rid[:8]} trace tree has {want}")
    shed_rec = next(r for r in recs.values() if r.shed)
    check(shed_rec.slo_class == "batch" and shed_rec.breach,
          "shed record is a batch-class TTFT breach")

    # -- surfaced: histogram through the tsdb + dashboard routes -------------
    clock = Clock()
    client = FakeKubeClient()
    store = TimeSeriesStore(clock=clock)
    store.sample_registry(DEFAULT_REGISTRY)
    api = DashboardApi(client, authorize=lambda *a: True, tsdb=store,
                       collector=collector, request_ledger=led)
    code, body = api.handle(
        "GET",
        "/api/metrics/query?metric=kftpu_request_ttft_ms_count"
        "&label=model:chat&label=slo_class:interactive", None)
    check(code == 200 and body["result"]
          and body["result"][0]["value"] == 3.0,
          "kftpu_request_ttft_ms reads back through /api/metrics/query")
    code, view = api.handle("GET", "/api/models/chat/requests", None)
    check(code == 200 and view["count"] == 5
          and view["phaseSeconds"]["decode"]["count"] == 5,
          "per-model request route serves phase percentiles")
    tid = view["worstTtft"]["traceId"]
    code, tree = api.handle("GET", f"/api/traces/{tid}", None)
    check(code == 200 and tree["spans"],
          "worst-TTFT exemplar resolves to the request trace")
    code, body = api.handle("GET", "/api/metrics/requests", None)
    check(code == 200 and body["fleet"]["count"] == 6
          and body["fleet"]["shed"] == 1,
          "fleet rollup counts served + shed")

    # -- the ttft-slo-burn walk ----------------------------------------------
    rule = next(r for r in default_rules()
                if r.name == "ttft-slo-burn-interactive")
    mgr = AlertManager(store, [rule], client=client, namespace="smoke",
                       clock=clock, tracer=tracer)
    transitions = []
    seq = [0]

    def finish(breach):
        seq[0] += 1
        rid = f"{seq[0]:032x}"
        led.start(rid, t=clock.now, model="synthetic",
                  slo_class="interactive")
        if not breach:
            led.emit(rid, clock.now + 0.1)   # 100 ms, under the 500 ms
        led.finish(rid, clock.now + 1.0)     # no token at all -> breach

    def tick(dt=30.0):
        clock.now += dt
        store.sample_registry(DEFAULT_REGISTRY)
        for st in mgr.evaluate():
            transitions.append((st.rule.name, st.state))

    for _ in range(4):                       # clean baseline traffic
        finish(breach=False)
        tick()
    for _ in range(8):                       # the breach storm
        finish(breach=True)
        finish(breach=True)
        tick()
    check(("ttft-slo-burn-interactive", PENDING) in transitions,
          "burn rule went Pending on the breach storm")
    check(("ttft-slo-burn-interactive", FIRING) in transitions,
          "burn rule fired on the breach storm")
    for _ in range(70):                      # recovery: clean stepping
        for _ in range(5):
            finish(breach=False)
        tick()
    check(("ttft-slo-burn-interactive", RESOLVED) in transitions,
          "burn rule resolved when TTFT recovered")
    names = [s for (r, s) in transitions
             if r == "ttft-slo-burn-interactive"]
    check(names == [PENDING, FIRING, RESOLVED],
          "exactly Pending -> Firing -> Resolved, in order")
    events = [e for e in client.list("v1", "Event", "smoke")
              if e["reason"].startswith("Alert")]
    check(sorted(e["reason"] for e in events)
          == ["AlertFiring", "AlertPending", "AlertResolved"],
          "exactly one Event per transition")

    print("request smoke: PASS")


if __name__ == "__main__":
    main()
