"""Preflight smoke gate for the paged decode engine (CPU, one minute).

Exercises the full slot lifecycle against the page allocator's own
invariants: admit (prefix-shared) → chunked prefill → decode → retire,
then asserts every page refcount returns to zero — a leaked or copied
page fails the gate. Greedy output is checked against the unary
``generate`` oracle so the lifecycle proof is also a correctness proof.

Two passes: the gather path (the bit-parity oracle), then the Pallas
paged-attention KERNEL path (``paged_attention_impl="kernel"``, running
through the Pallas interpreter on CPU) with a copy-on-write boundary
split in play — the non-aligned shared prefix forces exactly one
device-side page copy per sharing admission, and the pool must still
reclaim every page.

Run: JAX_PLATFORMS=cpu python scripts/paged_smoke.py
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from kubeflow_tpu.models import Transformer, TransformerConfig
from kubeflow_tpu.models.decode import generate
from kubeflow_tpu.serving.engine import DecodeEngine


def main() -> None:
    config = TransformerConfig(vocab_size=61, d_model=32, n_layers=2,
                               n_heads=4, n_kv_heads=2, d_ff=64,
                               max_seq_len=32, dtype=jnp.float32,
                               remat=False)
    params = Transformer(config).init(
        jax.random.key(0), np.zeros((1, 8), np.int32))["params"]
    eng = DecodeEngine(config, params, slots=2, paged=True,
                       kv_page_size=8, prefill_chunk_tokens=8,
                       autostart=False)

    def oracle(prompt, n):
        out = generate(config, params,
                       jnp.asarray([prompt], jnp.int32),
                       max_new_tokens=n)
        return np.asarray(out)[0].tolist()

    def drain(n=40):
        for _ in range(n):
            eng.run_once(timeout=0.01)

    prefix = list(range(1, 9))                 # 8 tokens = 1 full page
    p1, p2 = prefix + [5, 11], prefix + [9, 3]

    r1 = eng.submit(p1, max_new=4, prefix_len=8)   # miss: pins 1 page
    drain()
    assert r1.result() == oracle(p1, 4), "prefix-miss stream diverged"
    assert eng.prefill_chunks >= 2, "prompt was not chunk-prefilled"
    assert eng.prefix_misses == 1 and len(eng._prefix_pages) == 1

    r2 = eng.submit(p2, max_new=4, prefix_len=8)   # hit: shares the page
    drain()
    assert r2.result() == oracle(p2, 4), "prefix-hit stream diverged"
    assert eng.prefix_hits == 1, "prefix store was not hit"

    # retire accounting: only the store's pin remains, then nothing
    assert eng._pool.pages_in_use == eng._prefix_pages.pages_held == 1
    eng._prefix_pages.clear()
    eng._pool.check_idle()                     # every refcount at zero
    assert (eng._pool.ref == 0).all()

    # kernel-path pass with a COW split in play: a NON-aligned shared
    # prefix (page + 3 boundary tokens) trie-shares the full page,
    # maps the boundary page copy-on-write, and splits it exactly once
    keng = DecodeEngine(config, params, slots=2, paged=True,
                        kv_page_size=8, prefill_chunk_tokens=8,
                        paged_attention_impl="kernel", autostart=False)
    cpfx = list(range(20, 31))                 # 11 tokens: 1 page + 3
    c1, c2 = cpfx + [5, 2], cpfx + [7, 9]
    k1 = keng.submit(c1, max_new=4, prefix_len=11)
    for _ in range(40):
        keng.run_once(timeout=0.01)
    assert k1.result() == oracle(c1, 4), "kernel-path stream diverged"
    k2 = keng.submit(c2, max_new=4, prefix_len=11)
    for _ in range(40):
        keng.run_once(timeout=0.01)
    assert k2.result() == oracle(c2, 4), (
        "kernel-path COW-shared stream diverged")
    assert keng.prefix_hits == 1 and keng.cow_splits == 1, (
        f"expected one COW split on the boundary page, got "
        f"{keng.cow_splits} (hits={keng.prefix_hits})")
    keng._pool.check_invariants()
    keng._prefix_pages.clear()
    keng._pool.check_idle()                    # every refcount at zero
    assert (keng._pool.ref == 0).all()
    print("paged engine smoke: ok "
          f"(chunks={eng.prefill_chunks}, "
          f"pages_total={eng._pool.pages_total}, "
          f"kernel cow_splits={keng.cow_splits})")


if __name__ == "__main__":
    main()
