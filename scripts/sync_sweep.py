"""Chip experiment: decode-engine steps_per_sync sweep + dispatch RTT probe.

The r5 measurement decomposed the engine's 1516 tok/s (slots=32,
sync=8) into a per-dispatch fixed wall cost plus a marginal per-step
cost; steps_per_sync is the designed amortization lever. This sweeps it
over EXACTLY the decode-engine bench's workload (the setup/throughput
helpers are shared with ``bench_decode_engine``) and prints one JSON
line per point for PERF.md. Env: SWEEP_CONCURRENCY, SWEEP_SLOTS.
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    import jax
    import jax.numpy as jnp

    from kubeflow_tpu.bench.suite import (
        engine_bench_setup,
        engine_throughput,
    )

    # -- dispatch RTT probe: tiny jit op, timed round-trips ---------------
    f = jax.jit(lambda x: x + 1)
    x = jnp.zeros((8,), jnp.float32)
    f(x).block_until_ready()
    ts = []
    for _ in range(20):
        t0 = time.perf_counter()
        f(x).block_until_ready()
        ts.append((time.perf_counter() - t0) * 1e3)
    ts.sort()
    print(json.dumps({"probe": "dispatch_rtt_ms",
                      "p50": round(ts[len(ts) // 2], 2),
                      "min": round(ts[0], 2), "max": round(ts[-1], 2)}),
          flush=True)

    concurrency = int(os.environ.get("SWEEP_CONCURRENCY", "48"))
    slots = int(os.environ.get("SWEEP_SLOTS", "32"))
    new_tokens = 128
    config, params, prompts = engine_bench_setup(concurrency=concurrency,
                                                 new_tokens=new_tokens)

    for sync in [int(a) for a in sys.argv[1:]] or [8, 16, 32, 64]:
        t0 = time.perf_counter()
        tps, steps, _, _ = engine_throughput(
            config, params, prompts, slots=slots, steps_per_sync=sync,
            new_tokens=new_tokens, sampler_bound=64, sampled=False,
            name=f"sweep{sync}")
        print(json.dumps({
            "steps_per_sync": sync, "slots": slots,
            "tokens_per_sec_per_chip": tps,
            "engine_steps": steps,
            "wall_s": round(time.perf_counter() - t0, 2)}), flush=True)


if __name__ == "__main__":
    main()
