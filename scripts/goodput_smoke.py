#!/usr/bin/env python
"""Goodput-ledger smoke gate (scripts/preflight.sh stage 10).

One manually-set fake clock drives a 2-slice elastic TpuJob through its
whole badput repertoire: queue-wait behind a blocker, first-program
compile, productive steps, a checkpoint-preempt-requeue round trip, a
restore, and an elastic shrink — then checks the ledger the operator
folded into ``status.goodput`` (docs/OBSERVABILITY.md "Goodput"):

- all four scheduling-induced badput states appear (``queue_wait``,
  ``preempted``, ``resizing``, ``checkpoint_save``) plus ``restore``;
- fractions sum to 1.0 and intervals tile the wall clock exactly;
- ``kftpu_job_goodput_seconds_total{state}`` reads back through the
  tsdb and ``GET /api/metrics/query``;
- the ``job-badput-burn`` burn-rate rule walks
  ``Pending -> Firing -> Resolved`` on an injected checkpoint stall
  with exactly one k8s Event per transition.

Exits nonzero on any violated invariant.
"""

import math
import sys

sys.path.insert(0, ".")

from kubeflow_tpu.dashboard.server import DashboardApi  # noqa: E402
from kubeflow_tpu.k8s import FakeKubeClient  # noqa: E402
from kubeflow_tpu.obs import goodput as gp  # noqa: E402
from kubeflow_tpu.obs.alerts import (  # noqa: E402
    FIRING,
    INACTIVE,
    PENDING,
    RESOLVED,
    AlertManager,
    default_rules,
)
from kubeflow_tpu.obs.steps import publish_beacon  # noqa: E402
from kubeflow_tpu.obs.trace import SpanCollector, Tracer  # noqa: E402
from kubeflow_tpu.obs.tsdb import TimeSeriesStore  # noqa: E402
from kubeflow_tpu.operators.tpujob import (  # noqa: E402
    JOB_LABEL,
    PreemptionCheckpointer,
    TpuJobOperator,
    tpujob,
)
from kubeflow_tpu.manifests.components.tpujob_operator import (  # noqa: E402
    API_VERSION,
    TPUJOB_KIND,
)
from kubeflow_tpu.platform.local import fake_slice_nodes  # noqa: E402
from kubeflow_tpu.scheduler.queue import GangQueue  # noqa: E402
from kubeflow_tpu.utils import DEFAULT_REGISTRY  # noqa: E402


class Clock:
    def __init__(self, now=1000.0):
        self.now = now

    def __call__(self):
        return self.now


class NoDiskCkpt(PreemptionCheckpointer):
    def save(self, job):
        return None

    def latest_step(self, ns, name):
        return None


def check(ok, what):
    if not ok:
        print(f"FAIL: {what}")
        sys.exit(1)
    print(f"ok: {what}")


def main():
    ns = "smoke"
    client = FakeKubeClient()
    for node in fake_slice_nodes("v5e-8", count=2):
        client.create(node)
    clock = Clock()
    collector = SpanCollector()
    tracer = Tracer(collector, clock=clock)
    q = GangQueue(client, clock=clock, tracer=tracer,
                  checkpoint_step=lambda ns, name: None)
    op = TpuJobOperator(client, clock=clock, tracer=tracer, queue=q,
                        checkpointer=NoDiskCkpt())
    store = TimeSeriesStore(clock=clock)
    rule = next(r for r in default_rules()
                if r.name == "job-badput-burn")
    mgr = AlertManager(store, [rule], client=client, namespace=ns,
                       clock=clock, tracer=tracer)
    transitions = []

    def pods(name):
        return client.list("v1", "Pod", ns,
                           label_selector={JOB_LABEL: name})

    def phase(name, p):
        for pod in pods(name):
            pod.setdefault("status", {})["phase"] = p
            client.update_status(pod)

    def tick(dt=10.0, job="train"):
        clock.now += dt
        op.reconcile(ns, job)
        store.sample_registry(DEFAULT_REGISTRY)
        for st in mgr.evaluate():
            transitions.append((st.rule.name, st.state))

    # a blocker owns both slices; the 2-slice elastic job queues
    client.create(tpujob("block", ns, {
        "image": "x", "slices": 2, "hostsPerSlice": 1, "priority": 5}))
    op.reconcile(ns, "block")
    phase("block", "Running")
    client.create(tpujob("train", ns, {
        "image": "x", "slices": 2, "hostsPerSlice": 1,
        "elastic": {"minSlices": 1, "maxSlices": 2}}))
    op.reconcile(ns, "train")
    uid = client.get(API_VERSION, TPUJOB_KIND, ns,
                     "train")["metadata"]["uid"]
    tick()                                   # queue_wait
    check(pods("train") == [], "gang queues behind the blocker")
    client.delete(API_VERSION, TPUJOB_KIND, ns, "block")
    op.reconcile(ns, "block")
    tick()                                   # queue_wait, then placed
    check(len(pods("train")) == 2, "gang places when the blocker exits")
    phase("train", "Running")
    tick()                                   # startup_compile

    step = 0

    def advance(n=3):
        nonlocal step
        step += n
        for w in range(len(pods("train"))):
            publish_beacon(client, ns, "train", w,
                           {"step": step, "stepsPerSec": 1.0},
                           job_uid=uid)

    advance()
    tick()                                   # productive

    # checkpoint-preempt-requeue: a priority-10 gang takes both slices
    client.create(tpujob("urgent", ns, {
        "image": "x", "slices": 2, "hostsPerSlice": 1,
        "priority": 10}))
    clock.now += 10.0
    op.reconcile(ns, "urgent")
    tick(dt=0.0)                             # victim checkpoints + tears down
    check(pods("train") == [], "victim torn down for the preemptor")
    tick()                                   # preempted
    op.reconcile(ns, "urgent")
    check(len(pods("urgent")) == 2, "preemptor landed")
    client.delete(API_VERSION, TPUJOB_KIND, ns, "urgent")
    op.reconcile(ns, "urgent")
    tick()                                   # preempted, then re-placed
    check(len(pods("train")) == 2, "victim re-placed after the preemptor")
    phase("train", "Running")
    tick()                                   # restore
    advance()
    tick()                                   # productive

    # elastic shrink 2 -> 1 with an INJECTED CHECKPOINT STALL: the
    # worker snapshot eats whole reconcile windows, and the burn-rate
    # rule must notice the badput
    job = client.get(API_VERSION, TPUJOB_KIND, ns, "train")
    job["spec"] = {**job["spec"], "slices": 1}
    client.update(job)
    tick()                                   # nudge pass
    check(client.get(API_VERSION, TPUJOB_KIND, ns,
                     "train")["status"]["resize"]["requested"] is True,
          "resize nudged")
    gp.observe_checkpoint_save(600.0, namespace=ns, job="train",
                               source="worker")   # the stall
    tick()                                   # checkpoint_save + teardown
    tick()                                   # resizing, then re-gang at 1
    check(len(pods("train")) == 1, "re-ganged at 1 slice")
    phase("train", "Running")
    # the stall keeps carving checkpoint_save out of the next windows
    for _ in range(60):
        tick()
    check(("job-badput-burn", PENDING) in transitions,
          "burn rule went Pending on the stall")
    check(("job-badput-burn", FIRING) in transitions,
          "burn rule fired on the stall")

    # recovery: productive steps until the stall slides far enough out
    # of the 30m ticket window (600 s of badput needs ~21 min of clean
    # stepping before the trailing-1800s ratio drops under 3x budget)
    for _ in range(200):
        advance(1)
        tick()
    check(("job-badput-burn", RESOLVED) in transitions,
          "burn rule resolved when stepping resumed")
    names = [s for (r, s) in transitions if r == "job-badput-burn"]
    check(names == [PENDING, FIRING, RESOLVED],
          "exactly Pending -> Firing -> Resolved, in order")
    events = [e for e in client.list("v1", "Event", ns)
              if e["reason"].startswith("Alert")]
    check(sorted(e["reason"] for e in events)
          == ["AlertFiring", "AlertPending", "AlertResolved"],
          "exactly one Event per transition")
    check(mgr._states["job-badput-burn"].state in (RESOLVED, INACTIVE),
          "rule settled after recovery")

    # the ledger: all four scheduling badput states + restore, tiling
    g = client.get(API_VERSION, TPUJOB_KIND, ns,
                   "train")["status"]["goodput"]
    for st in ("queue_wait", "preempted", "resizing", "checkpoint_save",
               "restore", "productive_step"):
        check(g["seconds"].get(st, 0.0) > 0, f"ledger shows {st}")
    fr = gp.fractions(g)
    check(math.isclose(sum(fr.values()), 1.0, abs_tol=1e-9),
          "fractions sum to 1.0")
    ivs = g["intervals"]
    check(ivs[0]["start"] == g["start"] and ivs[-1]["end"] == g["asOf"]
          and all(a["end"] == b["start"] for a, b in zip(ivs, ivs[1:])),
          "intervals tile [start, asOf] with no gaps or overlaps")
    check(math.isclose(sum(g["seconds"].values()),
                       g["asOf"] - g["start"], abs_tol=1e-6),
          "seconds sum to the wall clock")

    # surfaced: counter through the tsdb query API + dashboard routes
    # (one catch-up pass first: the export lags the persisted ledger
    # by one reconcile by design)
    op.reconcile(ns, "train")
    store.sample_registry(DEFAULT_REGISTRY)
    api = DashboardApi(client, authorize=lambda *a: True, tsdb=store,
                       collector=collector)
    code, body = api.handle(
        "GET",
        "/api/metrics/query?metric=kftpu_job_goodput_seconds_total"
        f"&label=namespace:{ns}&label=job:train"
        "&label=state:productive_step", None)
    check(code == 200 and body["result"]
          and body["result"][0]["value"]
          == g["seconds"]["productive_step"],
          "counter reads back through /api/metrics/query")
    code, body = api.handle("GET", f"/api/jobs/{ns}/train/goodput",
                            None)
    check(code == 200 and body["worstBadput"] is not None,
          "per-job goodput route serves the timeline + exemplar")
    tid = body["worstBadput"]["traceId"]
    code, tree = api.handle("GET", f"/api/traces/{tid}", None)
    check(code == 200 and tree["spans"],
          "worst-interval exemplar resolves to the job trace")
    code, body = api.handle("GET", "/api/metrics/goodput", None)
    check(code == 200 and body["jobs"] >= 1
          and 0.0 < body["goodputFraction"] < 1.0,
          "fleet rollup answers with a chips-weighted fraction")

    print("goodput smoke: PASS")


if __name__ == "__main__":
    main()
